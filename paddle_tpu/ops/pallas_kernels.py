"""Hand-written Pallas TPU kernels for the fused-op set.

Parity: the reference's fused CUDA kernel library
(paddle/phi/kernels/fusion/ — flash attention #18, fused_rms_norm #17).
These are the only hand-written kernels in the framework; everything else
is XLA.  Each kernel has an XLA fallback (the callers catch exceptions), so
CPU tests exercise the same API.

Design notes (see /opt/skills/guides/pallas_guide.md):
- flash attention: one (batch*heads, q_block) grid cell holds a q tile in
  VMEM and streams k/v tiles, keeping the running max/denominator in fp32
  (online softmax).  Causal masking skips fully-masked k tiles.
- rms_norm: row-tiled, stats in fp32.
- flash backward: FlashAttention-2 two-kernel scheme in Pallas (dq over q
  tiles, dk/dv over k tiles, p recomputed from the saved lse); masked or
  ragged configs fall back to the chunked XLA backward.
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend only
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.dispatch import apply_op
from ..core.jax_compat import axis_size as _axis_size, shard_map_compat
from ..core.tensor import Tensor
from ._helpers import targ
from .online_softmax import online_softmax_update


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _x64_off():
    """Context manager tracing with x64 disabled (mosaic cannot legalize
    the i64 scalars python-int arithmetic produces under jax_enable_x64).
    jax >= 0.5 spells it jax.enable_x64(False); 0.4.x only has the
    experimental form."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    return jax.experimental.disable_x64()


# ---------------------------------------------------------------------------
# flash attention
# ---------------------------------------------------------------------------
_DIMNUM_NT = (((1,), (1,)), ((), ()))    # x @ y.T
_DIMNUM_NN = (((1,), (0,)), ((), ()))    # x @ y
_DIMNUM_TN = (((0,), (0,)), ((), ()))    # x.T @ y
# np.float32 (not python float): a weak-typed scalar staged from inside
# an OUTER x64 trace (ring attention's shard_map/cond around interpret-
# mode pallas) lowers as tensor<f64> and fails MLIR verification
_MASK_VALUE = np.float32(-0.7 * float(np.finfo(np.float32).max))
_MASK_THRESH = np.float32(0.5) * _MASK_VALUE   # any real score is above this
_F32_0 = np.float32(0.0)
_F32_NEG_INF = np.float32(-np.inf)
_LANES = 128
# Scores are kept in exp2 space: scale*log2(e) is folded into the q (or k)
# tile ONCE per VMEM tile, so the inner loop runs exp2 directly — saving
# the per-[bq,bk]-block scale multiply AND the log2e multiply XLA would
# emit inside exp.  lse residuals stay in natural-log space at the API
# boundary (the *_LN2 conversion happens at store).
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453


def _fit_block(want, total):
    """Largest usable block <= want that divides total.  Usable means the
    kernels' 128-lane VMEM softmax scratch can be adapted to it by _cols:
    either a multiple of 128 (tile) or <= 128 (slice).  A block >128 that
    is not a lane multiple (e.g. the whole axis when total=192) would
    crash at trace time, so it is never returned; sub-axis blocks must
    also be sublane-tileable (multiple of 16, covering f32 and bf16).
    Returns 0 when no divisor qualifies — dispatchers must pre-check
    shapes via _pallas_ok (which falls back to _chunked_sdpa); the
    kernel wrappers themselves raise on a 0 block."""
    b = min(want, total)
    if total % b == 0 and (b % _LANES == 0
                           or (b <= _LANES
                               and (b == total or b % 16 == 0))):
        return b
    for c in range((b // _LANES) * _LANES, 0, -_LANES):
        if total % c == 0:
            return c
    # sub-128 blocks smaller than the full axis must still be sublane
    # tileable: multiples of 16 cover both f32 (8,128) and bf16 (16,128)
    for c in range((min(b, _LANES) // 16) * 16, 0, -16):
        if total % c == 0:
            return c
    return 0


def _cols(x128, n):
    """Adapt a [rows, 128] lane-broadcast stat to n columns (n may be a
    sub-lane block size like 64, or a multiple of 128)."""
    if n < _LANES:
        return x128[:, :n]
    return jnp.tile(x128, (1, n // _LANES))


def _rope_tile(t_ref, cos_ref, sin_ref, neg_sin=False):
    """Neox-style rotary embedding applied to one [rows, d] tile in VMEM
    (the in-kernel fusion that replaces the XLA slice/negate/concat
    pattern — a 41 GiB/s HBM-bound fusion when done at graph level).
    neg_sin=True applies the inverse rotation (the rope VJP)."""
    t = t_ref if isinstance(t_ref, jnp.ndarray) else t_ref[...]
    tf = t.astype(jnp.float32)
    half = tf.shape[-1] // 2
    rot = jnp.concatenate([-tf[:, half:], tf[:, :half]], axis=1)
    c = cos_ref[...]
    sn = sin_ref[...]
    if neg_sin:
        return tf * c - rot * sn
    return tf * c + rot * sn


def _causal_run(qi, kb, block_q, block_k, causal_off):
    """True iff q tile ``qi`` has any visible column in k tile ``kb``
    (the q tile's last row reaches the k tile's first column).  Single
    source of truth shared by the kernels' skip predicate and the
    streamed-block index remaps below — they MUST agree or a skipped
    grid step would read a remapped (wrong) tile."""
    return (qi + 1) * block_q - 1 + causal_off >= kb * block_k


def _need_mask(qi, kb, block_q, block_k, causal_off):
    """True iff the (qi, kb) block contains any masked entry (its first
    row does not reach its last column); fully-visible blocks skip the
    iota/compare/select masking and the dead-row guard."""
    return qi * block_q + causal_off < kb * block_k + block_k - 1


def _causal_stream_kv(i, j, block_q, block_k, causal_off, causal):
    """Index remap for a streamed k/v grid axis under causal masking: a
    skipped (fully-masked) k block re-fetches block 0 — the block the
    NEXT q row starts with — so skipped grid steps cost no DMA and
    double as prefetch (the in-tree flash kernel's kv_index_map trick;
    without it the upper triangle streams ~60% extra k/v bytes through
    a stalled pipeline).  ``i`` is the resident q-tile index, ``j`` the
    streamed k-tile index."""
    if not causal:
        return j
    return jnp.where(_causal_run(i, j, block_q, block_k, causal_off),
                     j, 0)


def _causal_stream_q(i, j, block_q, block_k, causal_off, causal):
    """Index remap for a streamed q grid axis (k-tile-resident backward
    kernels): skipped ABOVE-diagonal q blocks re-fetch the first running
    q block of this k row.  ``i`` is the resident k-tile index, ``j``
    the streamed q-tile index."""
    if not causal:
        return j
    first = jnp.maximum(0, (i * block_k - causal_off) // block_q)
    return jnp.where(_causal_run(j, i, block_q, block_k, causal_off),
                     j, first)


def _flash_fwd_kernel(*refs, block_k: int, causal: bool, scale: float,
                      kv_blocks: int, causal_off: int = 0,
                      with_rope: bool = False):
    """Grid (BH, q_tile, k_tile): one k/v block per grid step, online
    softmax state in VMEM scratch across the (sequential) k dimension.

    The k axis as a grid dimension (not an in-kernel loop) lets Mosaic
    double-buffer the k/v HBM->VMEM DMAs against compute — the same
    pipelining structure as the in-tree pallas flash kernel.  Matmuls
    keep bf16 operands with f32 accumulation (preferred_element_type);
    an f32 upcast before the dot would quarter the MXU rate.  With
    with_rope, neox rotary embeddings are applied to the q/k tiles in
    VMEM (cos/sin tiles ride the grid like k/v)."""
    q_ref, k_ref, v_ref = refs[0:3]
    i = 3
    if with_rope:
        cos_i_ref, sin_i_ref, cos_j_ref, sin_j_ref = refs[3:7]
        i = 7
    o_ref = refs[i]
    rest = refs[i + 1:]
    qs_s = rest[-1]    # exp2-space q tile (scaled by scale*log2e; +rope)
    rest = rest[:-1]
    save_lse = len(rest) == 4
    if save_lse:
        lse_ref, m_s, l_s, acc_s = rest
    else:
        m_s, l_s, acc_s = rest
        lse_ref = None
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    bq, d = q_ref.shape[1], q_ref.shape[-1]
    c = scale * _LOG2E

    @pl.when(kb == 0)
    def _init():
        m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)
        # scale (and rope) q once per q tile — per-k-block rope dominated
        # the kernel, and a per-block scale multiply would cost a full
        # [bq, bk] VPU pass where this is [bq, d] once
        if with_rope:
            qs_s[...] = (_rope_tile(q_ref[0], cos_i_ref, sin_i_ref)
                         * c).astype(qs_s.dtype)
        else:
            qs_s[...] = (q_ref[0].astype(jnp.float32)
                         * c).astype(qs_s.dtype)

    run = True
    if causal:
        run = _causal_run(qi, kb, bq, block_k, causal_off)

    def _tile_body(mask: bool):
        q = qs_s[...]
        if with_rope:
            k = _rope_tile(k_ref[0], cos_j_ref, sin_j_ref).astype(
                k_ref.dtype)
        else:
            k = k_ref[0]                               # [bk, d]
        v = v_ref[0]
        # scores arrive pre-scaled into exp2 space via qs_s
        s = lax.dot_general(q, k, _DIMNUM_NT,
                            preferred_element_type=jnp.float32)
        if mask:
            rows = qi * bq + causal_off + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, _MASK_VALUE)
        m_prev = m_s[...]                              # [bq, 128]
        l_prev = l_s[...]
        m_curr = jnp.max(s, axis=1)[:, None]           # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)           # [bq, 128]
        p = jnp.exp2(s - _cols(m_next, block_k))
        if mask:
            # rows whose every score so far is masked must contribute
            # nothing (a finite mask value would otherwise give
            # p = exp2(0) = 1).  Dead rows can only exist in blocks with
            # masked entries, so the guard lives in the masked body only.
            p = jnp.where(_cols(m_next, block_k) > _MASK_THRESH, p, _F32_0)
        alpha = jnp.exp2(m_prev - m_next)              # [bq, 128]
        m_s[...] = m_next
        l_s[...] = jnp.sum(p, axis=1)[:, None] + alpha * l_prev
        # FA2 deferred normalization: accumulate unnormalized, divide by
        # l once at store — saves a reciprocal + [bq, d] multiply per block
        pv = lax.dot_general(p.astype(v.dtype), v, _DIMNUM_NN,
                             preferred_element_type=jnp.float32)
        acc_s[...] = acc_s[...] * _cols(alpha, d) + pv

    if causal:
        # skip the iota/compare/select masking entirely on fully-visible
        # blocks (the majority for block-aligned causal self-attention)
        need_mask = _need_mask(qi, kb, bq, block_k, causal_off)
        @pl.when(run & need_mask)
        def _body_masked():
            _tile_body(True)

        @pl.when(run & jnp.logical_not(need_mask))
        def _body_full():
            _tile_body(False)
    else:
        _tile_body(False)

    @pl.when(kb == kv_blocks - 1)
    def _store():
        l_v = l_s[...]
        l_inv = jnp.where(l_v > _F32_0, np.float32(1.0) / l_v, _F32_0)
        o_ref[0] = (acc_s[...] * _cols(l_inv, d)).astype(o_ref.dtype)
        if save_lse:
            # natural-log log-sum-exp residual for the backward (scores
            # live in exp2 space in-kernel: convert m back with ln2),
            # lane-broadcast to the mosaic-tileable 128-lane layout;
            # -inf marks rows that attended nothing
            lse = jnp.where(l_v > _F32_0,
                            m_s[...] * np.float32(_LN2) + jnp.log(l_v),
                            _F32_NEG_INF)
            lse_ref[0] = lse.astype(jnp.float32)


_INTERPRET = [False]  # set True in CPU tests to run kernels interpreted


def _flash_attention_value(q, k, v, causal: bool, block_q=512,
                           block_k=512, with_lse: bool = False,
                           rope=None):
    """q,k,v: [B, H, S, D] -> [B, H, S, D]
    (+ optional compact lse [B*H, Sq] when with_lse).
    rope=(cos, sin) with [S, D] f32 tables applies neox rotary to q/k
    inside the kernel (requires Sq == Sk)."""
    if not _HAS_PLTPU:
        raise RuntimeError(
            "pallas TPU support unavailable; use the chunked path")
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = _fit_block(block_q, Sq)
    block_k = _fit_block(block_k, Sk)
    if not block_q or not block_k:
        raise ValueError(f"no usable pallas block for Sq={Sq}, Sk={Sk}")
    if rope is not None and Sq != Sk:
        raise ValueError("in-kernel rope requires Sq == Sk")
    scale = 1.0 / math.sqrt(D)
    n_kb = Sk // block_k

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               kv_blocks=n_kb, causal_off=Sk - Sq,
                               with_rope=rope is not None)
    causal_off = Sk - Sq

    def _kv_j(i, j):
        return _causal_stream_kv(i, j, block_q, block_k, causal_off,
                                 causal)

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, D),
                           lambda b, i, j: (b, _kv_j(i, j), 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q.reshape(B * H, Sq, D), k.reshape(B * H, Sk, D),
            v.reshape(B * H, Sk, D)]
    if rope is not None:
        cos, sin = rope
        cs_i = pl.BlockSpec((block_q, D), lambda b, i, j: (i, 0))
        cs_j = pl.BlockSpec((block_k, D),
                            lambda b, i, j: (_kv_j(i, j), 0))
        in_specs += [cs_i, cs_i, cs_j, cs_j]
        args += [cos, sin, cos, sin]
    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype)]
    if with_lse:
        out_specs.append(pl.BlockSpec((1, block_q, 128),
                                      lambda b, i, j: (b, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, Sq, 128),
                                              jnp.float32))
    # Kernel body traced with x64 off: mosaic cannot legalize the i64
    # scalars that python-int arithmetic produces under jax_enable_x64.
    with _x64_off():
        res = pl.pallas_call(
            kernel,
            grid=(B * H, Sq // block_q, n_kb),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((block_q, 128), jnp.float32),
                            pltpu.VMEM((block_q, 128), jnp.float32),
                            pltpu.VMEM((block_q, D), jnp.float32),
                            pltpu.VMEM((block_q, D), q.dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
            if (_HAS_PLTPU and not _INTERPRET[0]) else None,
            interpret=_INTERPRET[0],
        )(*args)
    out = res[0].reshape(B, H, Sq, D)
    if with_lse:
        # compact residual [BH, Sq]: the lane broadcast is re-expanded
        # transiently in the backward (keeping it would cost 128x the
        # memory across every layer's saved residuals)
        return out, res[1][..., 0]
    return out


def _bwd_p_ds(q2, k, v, do, lse2, delta, *, mask, row_off, col_off):
    """Shared backward tile math (used by all backward kernels):
    recompute p from the saved lse, then ds = p * (dp - delta).

    exp2-space convention: EXACTLY ONE of q2/k carries the scale*log2e
    factor (folded in once per VMEM tile by the caller) and lse2 is the
    [bq, 128] lane-broadcast residual already multiplied by log2e, so
    p = exp2(q2.k - lse2) = softmax probabilities with no per-block
    scale pass.  ds is returned in natural d/ds space (p is unitless).
    ``mask`` is a static flag: fully-visible causal blocks skip the
    iota/compare/select AND the dead-row guard (dead rows can only
    exist in blocks that contain masked entries).  delta is [bq, 1]."""
    bq, bk = q2.shape[0], k.shape[0]
    s = lax.dot_general(q2, k, _DIMNUM_NT,
                        preferred_element_type=jnp.float32)
    if mask:
        rows = row_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = col_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, _MASK_VALUE)
        # dead rows have lse = -inf: exp2(s - lse2) would be inf -> 0
        finite = jnp.isfinite(lse2[:, :1])
        p = jnp.where(finite, jnp.exp2(s - _cols(lse2, bk)), _F32_0)
    else:
        p = jnp.exp2(s - _cols(lse2, bk))
    dp = lax.dot_general(do, v, _DIMNUM_NT,
                         preferred_element_type=jnp.float32)
    ds = (p * (dp - delta)).astype(k.dtype)
    return p, ds


def _flash_bwd_dq_kernel(*refs, block_k: int,
                         causal: bool, scale: float, kv_blocks: int,
                         causal_off: int, with_rope: bool = False):
    """dQ, grid (BH, q_tile, k_tile): k/v stream through as grid blocks,
    dq accumulates in VMEM scratch (FlashAttention-2 q-parallel half; p
    recomputed from the saved lse, delta = rowsum(dO*O) computed in the
    kernel from the o/do tiles — no precomputed broadcast array)."""
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = refs[0:6]
    i = 6
    if with_rope:
        cos_i_ref, sin_i_ref, cos_j_ref, sin_j_ref = refs[6:10]
        i = 10
    dq_ref = refs[i]
    dq_s, delta_s, qs_s = refs[i + 1:]
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    bq, d = q_ref.shape[1], q_ref.shape[-1]
    c = scale * _LOG2E

    @pl.when(kb == 0)
    def _init():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)
        do32 = do_ref[0].astype(jnp.float32)
        o32 = o_ref[0].astype(jnp.float32)
        delta_s[...] = jnp.broadcast_to(
            jnp.sum(do32 * o32, axis=1)[:, None], delta_s.shape)
        # exp2-space q tile: scale*log2e (and rope) folded in once
        if with_rope:
            qs_s[...] = (_rope_tile(q_ref[0], cos_i_ref, sin_i_ref)
                         * c).astype(qs_s.dtype)
        else:
            qs_s[...] = (q_ref[0].astype(jnp.float32)
                         * c).astype(qs_s.dtype)

    run = True
    if causal:
        run = _causal_run(qi, kb, bq, block_k, causal_off)

    def _tile_body(mask: bool):
        if with_rope:
            k = _rope_tile(k_ref[0], cos_j_ref, sin_j_ref).astype(
                k_ref.dtype)
        else:
            k = k_ref[0]
        _, ds = _bwd_p_ds(qs_s[...], k, v_ref[0], do_ref[0],
                          lse_ref[0], delta_s[:, :1], mask=mask,
                          row_off=qi * bq + causal_off,
                          col_off=kb * block_k)
        dq_s[...] += lax.dot_general(
            ds, k, _DIMNUM_NN, preferred_element_type=jnp.float32) * scale

    if causal:
        need_mask = _need_mask(qi, kb, bq, block_k, causal_off)
        @pl.when(run & need_mask)
        def _body_masked():
            _tile_body(True)

        @pl.when(run & jnp.logical_not(need_mask))
        def _body_full():
            _tile_body(False)
    else:
        _tile_body(False)

    @pl.when(kb == kv_blocks - 1)
    def _store():
        if with_rope:
            # dq was accumulated in rope space; the rope VJP is the
            # inverse rotation (same tables, negated sin)
            dq_ref[0] = _rope_tile(dq_s[...], cos_i_ref, sin_i_ref,
                                   neg_sin=True).astype(dq_ref.dtype)
        else:
            dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _flash_bwd_kv_kernel(*refs, block_q: int,
                         causal: bool, scale: float, q_blocks: int,
                         causal_off: int, with_rope: bool = False,
                         emit_dq: bool = False):
    """dK/dV (+ optional dq partials), grid (BH, k_tile, q_tile):
    q/do/o/lse stream through as grid blocks, dk/dv accumulate in VMEM
    scratch.  With emit_dq this is the FUSED backward: the same pass
    also writes one f32 dq partial per (k_tile, q_tile) cell (reduced
    over the small k-tile axis outside) — 5 matmuls and one streaming
    pass instead of the 7 matmuls / two passes of the two-kernel
    FlashAttention-2 split."""
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = refs[0:6]
    i = 6
    if with_rope:
        # cos/sin tiles: _k indexes the k tile (this cell), _q the
        # streamed q tile
        cos_k_ref, sin_k_ref, cos_q_ref, sin_q_ref = refs[6:10]
        i = 10
    if emit_dq:
        dq_ref = refs[i]
        i += 1
    dk_ref, dv_ref = refs[i:i + 2]
    dk_s, dv_s, ks_s = refs[i + 2:]
    ki = pl.program_id(1)
    qb = pl.program_id(2)
    bk = k_ref.shape[1]
    c = scale * _LOG2E

    @pl.when(qb == 0)
    def _init():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)
        # here k is the resident tile, so the exp2-space scale*log2e
        # factor folds into K (q streams through unscaled)
        if with_rope:
            ks_s[...] = (_rope_tile(k_ref[0], cos_k_ref, sin_k_ref)
                         * c).astype(ks_s.dtype)
        else:
            ks_s[...] = (k_ref[0].astype(jnp.float32)
                         * c).astype(ks_s.dtype)

    run = True
    if causal:
        run = _causal_run(qb, ki, block_q, bk, causal_off)

    def _tile_body(mask: bool):
        if with_rope:
            q = _rope_tile(q_ref[0], cos_q_ref, sin_q_ref).astype(
                q_ref.dtype)
        else:
            q = q_ref[0]
        do = do_ref[0]
        # delta recomputed per (k,q) cell: the o tile is DMA'd for this
        # cell regardless (block specs fetch per grid step), so caching
        # the reduction in scratch would save only the VPU mul-reduce
        delta = jnp.sum(do.astype(jnp.float32)
                        * o_ref[0].astype(jnp.float32),
                        axis=1)[:, None]               # [bq, 1]
        p, ds = _bwd_p_ds(q, ks_s[...], v_ref[0], do,
                          lse_ref[0], delta, mask=mask,
                          row_off=qb * block_q + causal_off,
                          col_off=ki * bk)
        dv_s[...] += lax.dot_general(p.astype(do.dtype), do, _DIMNUM_TN,
                                     preferred_element_type=jnp.float32)
        dk_s[...] += lax.dot_general(
            ds, q, _DIMNUM_TN, preferred_element_type=jnp.float32) * scale
        if emit_dq:
            # ks_s carries the exp2-space factor c; dq wants ds @ k_rope
            # * scale, so correct by scale/c = 1/log2e
            dq = lax.dot_general(
                ds, ks_s[...], _DIMNUM_NN,
                preferred_element_type=jnp.float32) * (1.0 / _LOG2E)
            if with_rope:
                # inverse-rotate each partial in-kernel (linear, so it
                # commutes with the sum).  Measured: cheaper than one
                # XLA inverse pass over the f32 sum (-12ms/step there —
                # the graph-level slice/negate/concat fusion is the
                # HBM-bound pattern the in-kernel rope exists to avoid)
                dq = _rope_tile(dq, cos_q_ref, sin_q_ref, neg_sin=True)
            dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    if causal:
        need_mask = _need_mask(qb, ki, block_q, bk, causal_off)
        @pl.when(run & need_mask)
        def _body_masked():
            _tile_body(True)

        @pl.when(run & jnp.logical_not(need_mask))
        def _body_full():
            _tile_body(False)
    else:
        _tile_body(False)

    if emit_dq and causal:
        @pl.when(jnp.logical_not(run))
        def _dead():
            dq_ref[0, 0] = jnp.zeros(dq_ref.shape[2:], dq_ref.dtype)

    @pl.when(qb == q_blocks - 1)
    def _store():
        if with_rope:
            dk_ref[0] = _rope_tile(dk_s[...], cos_k_ref, sin_k_ref,
                                   neg_sin=True).astype(dk_ref.dtype)
        else:
            dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _flash_attention_bwd_fused(q, k, v, out, lse, g, causal: bool,
                               block_q=256, block_k=1024, rope=None):
    """Single-kernel flash backward (_flash_bwd_kv_kernel, emit_dq=True).
    f32 dq partials [n_kb, BH, Sq, D] are reduced by XLA right after —
    a cheap fused sum over the short k-tile axis (callers bound n_kb so
    this buffer stays a small multiple of dq)."""
    if not _HAS_PLTPU:
        raise RuntimeError(
            "pallas TPU support unavailable; use the chunked path")
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = _fit_block(block_q, Sq)
    block_k = _fit_block(block_k, Sk)
    if not block_q or not block_k:
        raise ValueError(f"no usable pallas block for Sq={Sq}, Sk={Sk}")
    scale = 1.0 / math.sqrt(D)
    causal_off = Sk - Sq
    n_qb = Sq // block_q
    n_kb = Sk // block_k
    BH = B * H

    args = (q.reshape(BH, Sq, D), k.reshape(BH, Sk, D),
            v.reshape(BH, Sk, D), out.reshape(BH, Sq, D),
            g.reshape(BH, Sq, D))
    with_rope = rope is not None
    # exp2-space residual (×log2e) built once at graph level — cheaper
    # than a per-grid-step [block_q, 128] multiply inside the kernel
    lser = jnp.broadcast_to((lse * _LOG2E).reshape(BH, Sq)[..., None],
                            (BH, Sq, 128))

    def qs(sel):
        return pl.BlockSpec((1, block_q, D),
                            lambda b, i, j: (b, sel(i, j), 0))

    def ks(sel):
        return pl.BlockSpec((1, block_k, D),
                            lambda b, i, j: (b, sel(i, j), 0))

    by_i = lambda i, j: i

    def by_j(i, j):
        return _causal_stream_q(i, j, block_q, block_k, causal_off,
                                causal)

    in_specs = [qs(by_j), ks(by_i), ks(by_i), qs(by_j), qs(by_j),
                pl.BlockSpec((1, block_q, 128),
                             lambda b, i, j: (b, by_j(i, j), 0))]
    call_args = (*args, lser)
    if with_rope:
        cos, sin = rope
        in_specs += [
            pl.BlockSpec((block_k, D), lambda b, i, j: (i, 0)),
            pl.BlockSpec((block_k, D), lambda b, i, j: (i, 0)),
            pl.BlockSpec((block_q, D), lambda b, i, j: (by_j(i, j), 0)),
            pl.BlockSpec((block_q, D), lambda b, i, j: (by_j(i, j), 0))]
        call_args += (cos, sin, cos, sin)

    with _x64_off():
        dq_part, dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_kv_kernel, block_q=block_q, causal=causal,
                scale=scale, q_blocks=n_qb, causal_off=causal_off,
                with_rope=with_rope, emit_dq=True),
            grid=(BH, n_kb, n_qb),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, block_q, D),
                             lambda b, i, j: (i, b, j, 0)),
                ks(by_i), ks(by_i)],
            out_shape=[
                jax.ShapeDtypeStruct((n_kb, BH, Sq, D), jnp.float32),
                jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
                jax.ShapeDtypeStruct((BH, Sk, D), v.dtype)],
            scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), k.dtype)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
            if (_HAS_PLTPU and not _INTERPRET[0]) else None,
            interpret=_INTERPRET[0],
        )(*call_args)

    dq = jnp.sum(dq_part, axis=0).astype(q.dtype)
    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


# fused-bwd routing: the dq-partials buffer is n_kb copies of dq, so cap
# n_kb (block_k grows with Sk) and beyond this Sk hand off to the
# two-kernel scheme whose memory stays O(S*D + S) regardless
_FUSED_BWD_MAX_SK = 8192
# block_q=512 measured ~7-11% faster than 256 on v5e at both D=64 and
# D=128 (tools/attn_sweep.py; BENCH_ATTN artifact).  Module constant so
# the VMEM audit (tools/check_vmem_budget.py) sees tile edits.
_FUSED_BWD_BLOCK_Q = 512


def _flash_bwd_auto(q, k, v, out, lse, g, causal, rope=None):
    """Pick the backward kernel: the fused single-kernel scheme (~2.4x
    faster on v5e) when the dq-partials buffer stays small (n_kb <= 4),
    else the two-kernel FlashAttention-2 split (O(S*D + S) memory)."""
    Sk = k.shape[2]
    if Sk <= _FUSED_BWD_MAX_SK:
        bk = _fit_block(max(1024, Sk // 4), Sk)
        # the cap must hold for the block actually found: awkward seq
        # lengths can snap to a much smaller divisor (e.g. Sk=2176 ->
        # bk=128, n_kb=17), where the partials buffer would dwarf dq
        if bk and Sk // bk <= 4:
            return _flash_attention_bwd_fused(q, k, v, out, lse, g,
                                              causal, _FUSED_BWD_BLOCK_Q,
                                              bk, rope=rope)
    return _flash_attention_bwd(q, k, v, out, lse, g, causal, rope=rope)


def _flash_attention_bwd(q, k, v, out, lse, g, causal: bool,
                         block_q=512, block_k=1024, rope=None):
    """Pallas flash backward (FlashAttention-2 two-kernel scheme):
    dq parallel over q tiles; dk/dv parallel over k tiles; both stream
    the reduction axis through the grid with VMEM scratch accumulators,
    recomputing p from the forward's lse — memory stays O(S·D + S)."""
    if not _HAS_PLTPU:
        raise RuntimeError(
            "pallas TPU support unavailable; use the chunked path")
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = _fit_block(block_q, Sq)
    block_k = _fit_block(block_k, Sk)
    if not block_q or not block_k:
        raise ValueError(f"no usable pallas block for Sq={Sq}, Sk={Sk}")
    scale = 1.0 / math.sqrt(D)
    causal_off = Sk - Sq
    n_qb = Sq // block_q
    n_kb = Sk // block_k

    args = (q.reshape(B * H, Sq, D), k.reshape(B * H, Sk, D),
            v.reshape(B * H, Sk, D), out.reshape(B * H, Sq, D),
            g.reshape(B * H, Sq, D))
    with_rope = rope is not None
    # lane-broadcast lse to the mosaic-tileable [BH, Sq, 128] layout, in
    # exp2 space (×log2e) so the kernels consume it without a per-step
    # multiply (transient per-layer; the saved residual stays compact)
    lser = jnp.broadcast_to((lse * _LOG2E).reshape(B * H, Sq)[..., None],
                            (B * H, Sq, 128))

    def qs(sel):
        return pl.BlockSpec((1, block_q, D),
                            lambda b, i, j: (b, sel(i, j), 0))

    def ks(sel):
        return pl.BlockSpec((1, block_k, D),
                            lambda b, i, j: (b, sel(i, j), 0))

    def rows(sel):
        return pl.BlockSpec((1, block_q, 128),
                            lambda b, i, j: (b, sel(i, j), 0))

    by_i = lambda i, j: i

    # causal skipped-block remaps: dq pass streams k tiles (skipped ks
    # are the LATE ones -> restart at block 0); kv pass streams q tiles
    # (skipped qs are the EARLY above-diagonal ones -> first running)
    def kb_j(i, j):
        return _causal_stream_kv(i, j, block_q, block_k, causal_off,
                                 causal)

    def qb_j(i, j):
        return _causal_stream_q(i, j, block_q, block_k, causal_off,
                                causal)

    def cs_q(sel):
        return pl.BlockSpec((block_q, D), lambda b, i, j: (sel(i, j), 0))

    def cs_k(sel):
        return pl.BlockSpec((block_k, D), lambda b, i, j: (sel(i, j), 0))

    params = dict(
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
        if (_HAS_PLTPU and not _INTERPRET[0]) else None,
        interpret=_INTERPRET[0])

    with _x64_off():
        dq_in_specs = [qs(by_i), ks(kb_j), ks(kb_j), qs(by_i), qs(by_i),
                       rows(by_i)]
        dq_args = (*args, lser)
        if with_rope:
            cos, sin = rope
            dq_in_specs += [cs_q(by_i), cs_q(by_i), cs_k(kb_j), cs_k(kb_j)]
            dq_args += (cos, sin, cos, sin)
        dq = pl.pallas_call(
            functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                              causal=causal, scale=scale, kv_blocks=n_kb,
                              causal_off=causal_off, with_rope=with_rope),
            grid=(B * H, n_qb, n_kb),
            in_specs=dq_in_specs,
            out_specs=qs(by_i),
            out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32),
                            pltpu.VMEM((block_q, 128), jnp.float32),
                            pltpu.VMEM((block_q, D), q.dtype)],
            **params,
        )(*dq_args)

        kv_in_specs = [qs(qb_j), ks(by_i), ks(by_i), qs(qb_j), qs(qb_j),
                       rows(qb_j)]
        kv_args = (*args, lser)
        if with_rope:
            cos, sin = rope
            kv_in_specs += [cs_k(by_i), cs_k(by_i), cs_q(qb_j), cs_q(qb_j)]
            kv_args += (cos, sin, cos, sin)
        dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_kv_kernel, block_q=block_q,
                              causal=causal, scale=scale, q_blocks=n_qb,
                              causal_off=causal_off, with_rope=with_rope),
            grid=(B * H, n_kb, n_qb),
            in_specs=kv_in_specs,
            out_specs=[ks(by_i), ks(by_i)],
            out_shape=[jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
                       jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype)],
            scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), k.dtype)],
            **params,
        )(*kv_args)

    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


def _sdpa_reference(q, k, v, causal):
    """Full-materialization XLA reference (tests / tiny shapes only)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _chunked_sdpa(q, k, v, causal, mask=None, block_k=256):
    """Memory-bounded attention: lax.scan over k/v blocks with online
    softmax; each block body is rematerialized (jax.checkpoint), so the
    BACKWARD also runs block-by-block — activation memory stays
    O(S·D + S) instead of the O(S²) of the naive formulation.  Handles
    additive/bool masks and seq lengths not divisible by the block.

    Layout [B, H, S, D].  This is both the flash VJP path and the
    fallback forward for masked/ragged configs.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bk = min(block_k, Sk)
    pad = (-Sk) % bk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_kb = (Sk + pad) // bk
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32) * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 0)
    off = jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 1)
    # bottom-right-aligned causal for Sq != Sk (decode), like _sdpa_reference
    causal_off = Sk - Sq

    if mask is not None:
        if mask.dtype != jnp.bool_:
            mask = mask.astype(jnp.float32)
        if pad:
            # pad the key axis so block slices never clamp; the padded
            # columns are killed by the `cols < Sk` validity test anyway
            widths = [(0, 0)] * (mask.ndim - 1) + [(0, pad)]
            mask = jnp.pad(mask, widths)

    def block(carry, kb):
        m_, l_, acc = carry
        ks = lax.dynamic_slice_in_dim(k, kb * bk, bk, 2)
        vs = lax.dynamic_slice_in_dim(v, kb * bk, bk, 2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, ks.astype(jnp.float32))
        cols = kb * bk + off
        valid = cols < Sk
        if causal:
            valid = valid & (rows + causal_off >= cols)
        if mask is not None:
            mb = lax.dynamic_slice_in_dim(mask, kb * bk,
                                          bk, mask.ndim - 1)
            if mb.dtype == jnp.bool_:
                valid = valid & mb
            else:
                s = s + mb
        s = jnp.where(valid, s, -jnp.inf)
        m_new = jnp.maximum(m_, jnp.max(s, -1))
        # guard fully-masked rows (m_new = -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(valid, p, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_), m_ - m_safe, -jnp.inf))
        alpha = jnp.where(jnp.isfinite(m_), alpha, 0.0)
        l_new = l_ * alpha + jnp.sum(p, -1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vs.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    # derive the carries from qf so they inherit its device-varying
    # status under shard_map (a literal zeros init would mismatch the
    # scan body's output vma when run inside ulysses/ring wrappers)
    zero_rows = qf[..., 0] * 0.0                      # [B, H, Sq] f32
    init = (zero_rows - jnp.inf,
            zero_rows,
            qf * 0.0)
    (m_, l_, acc), _ = lax.scan(jax.checkpoint(block), init,
                                jnp.arange(n_kb, dtype=jnp.int32))
    out = acc / jnp.maximum(l_, 1e-30)[..., None]
    return out.astype(q.dtype)


def _pallas_ok(q, k, mask, block=256) -> bool:
    return (_HAS_PLTPU and _on_tpu() and mask is None
            and q.shape[3] <= 128                      # scratch is 128-lane
            and _fit_block(block, q.shape[2]) > 0
            and _fit_block(block, k.shape[2]) > 0)


def _select_flash_blocks(q, k, v, causal):
    """(block_q, block_k) via the autotune cache (parity: the reference's
    kernel-autotune algo pick, paddle/phi/kernels/autotune/auto_tune_base.h).
    Inside a trace only the cached winner is consulted; with concrete
    buffers a miss triggers the timed search."""
    from ..incubate.autotune import (autotune_enabled, autotune_lookup,
                                     autotune_select,
                                     flash_attention_candidates)
    Sq, Sk = q.shape[2], k.shape[2]
    default = (min(512, Sq), min(512, Sk))
    if not autotune_enabled():
        return default
    sig = (tuple(q.shape), tuple(k.shape), str(q.dtype), bool(causal))
    if isinstance(q, jax.core.Tracer):
        return autotune_lookup("flash_attention", sig) or default
    return autotune_select(
        "flash_attention", sig,
        flash_attention_candidates(Sq, Sk),
        lambda cand: (lambda: _flash_attention_value(
            q, k, v, causal, cand[0], cand[1])),
        default)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_sdpa(q, k, v, causal):
    if _pallas_ok(q, k, None):
        bq, bk = _select_flash_blocks(q, k, v, causal)
        return _flash_attention_value(q, k, v, causal, bq, bk)
    return _chunked_sdpa(q, k, v, causal)


def _flash_sdpa_fwd(q, k, v, causal):
    if _pallas_ok(q, k, None):
        bq, bk = _select_flash_blocks(q, k, v, causal)
        out, lse = _flash_attention_value(q, k, v, causal, bq, bk,
                                          with_lse=True)
        return out, (q, k, v, out, lse)
    return _chunked_sdpa(q, k, v, causal), (q, k, v, None, None)


def _flash_sdpa_bwd(causal, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        # Pallas flash backward: p recomputed from lse per tile; fused
        # single-kernel scheme for bounded n_kb, two-kernel beyond
        return _flash_bwd_auto(q, k, v, out, lse, g, causal)
    # chunked backward: block recompute keeps memory bounded (fallback
    # for masked/ragged configs the Pallas kernel rejects)
    _, vjp = jax.vjp(lambda q_, k_, v_: _chunked_sdpa(q_, k_, v_, causal),
                     q, k, v)
    return vjp(g)


_flash_sdpa.defvjp(_flash_sdpa_fwd, _flash_sdpa_bwd)


# ---------------------------------------------------------------------------
# fused rope + flash attention (training fast path)
# ---------------------------------------------------------------------------
def rope_tables(seq_len, dim, base=10000.0, position_offset=0,
                dtype=jnp.float32):
    """Neox rotary cos/sin tables [S, D] (f32; fed to the fused kernel)."""
    inv = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    pos = jnp.arange(position_offset, position_offset + seq_len,
                     dtype=jnp.float32)
    freqs = pos[:, None] * inv[None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rope_xla(t, cos, sin):
    """Graph-level neox rope on [B, H, S, D] (fallback path)."""
    tf = t.astype(jnp.float32)
    half = tf.shape[-1] // 2
    rot = jnp.concatenate([-tf[..., half:], tf[..., :half]], axis=-1)
    return (tf * cos[None, None] + rot * sin[None, None]).astype(t.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _flash_rope_sdpa(q, k, v, cos, sin, causal):
    if _pallas_ok(q, k, None) and q.shape[2] == k.shape[2]:
        bq, bk = _select_flash_blocks(q, k, v, causal)
        return _flash_attention_value(q, k, v, causal, bq, bk,
                                      rope=(cos, sin))
    return _chunked_sdpa(_rope_xla(q, cos, sin), _rope_xla(k, cos, sin),
                         v, causal)


def _flash_rope_sdpa_fwd(q, k, v, cos, sin, causal):
    if _pallas_ok(q, k, None) and q.shape[2] == k.shape[2]:
        bq, bk = _select_flash_blocks(q, k, v, causal)
        out, lse = _flash_attention_value(q, k, v, causal, bq, bk,
                                          with_lse=True, rope=(cos, sin))
        return out, (q, k, v, cos, sin, out, lse)
    return (_chunked_sdpa(_rope_xla(q, cos, sin), _rope_xla(k, cos, sin),
                          v, causal), (q, k, v, cos, sin, None, None))


def _flash_rope_sdpa_bwd(causal, res, g):
    q, k, v, cos, sin, out, lse = res
    if lse is not None:
        dq, dk, dv = _flash_bwd_auto(q, k, v, out, lse, g, causal,
                                     rope=(cos, sin))
        return dq, dk, dv, jnp.zeros_like(cos), jnp.zeros_like(sin)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_sdpa(
            _rope_xla(q_, cos, sin), _rope_xla(k_, cos, sin), v_, causal),
        q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, jnp.zeros_like(cos), jnp.zeros_like(sin)


_flash_rope_sdpa.defvjp(_flash_rope_sdpa_fwd, _flash_rope_sdpa_bwd)


def flash_attention_rope(query, key, value, rotary_base=10000.0,
                         is_causal=True):
    """Fused neox-rope + flash attention, paddle layout [B, S, H, D].

    The rotary embedding is applied to the q/k tiles inside the Pallas
    kernels (fwd recompute in both backward halves, inverse rotation on
    the dq/dk stores), so the XLA graph carries NO rope ops at all —
    replacing the reference's separate fused_rotary_position_embedding +
    flash_attention pair (paddle/phi/kernels/fusion/) on the training
    path.  k/v must already be head-repeated for GQA (rope commutes with
    the repeat)."""
    def fn(q, k, v):
        S, D = q.shape[1], q.shape[3]
        cos, sin = rope_tables(S, D, rotary_base)
        out = _flash_rope_sdpa(jnp.swapaxes(q, 1, 2),
                               jnp.swapaxes(k, 1, 2),
                               jnp.swapaxes(v, 1, 2), cos, sin, is_causal)
        return jnp.swapaxes(out, 1, 2)

    return apply_op("flash_attention_rope", fn,
                    (query, targ(key), targ(value)))



def flash_attention_tpu(query, key, value, attn_mask=None, is_causal=False):
    """Flash attention, paddle layout [B, S, H, D].

    Clean configs (no mask, block-divisible) hit the Pallas forward and
    the Pallas FlashAttention-2 backward on TPU; masked or ragged-length
    configs run the chunked online-softmax path with its block-recomputed
    backward — still memory-bounded, still one dispatched op."""

    def fn(q, k, v, *m):
        q_ = jnp.swapaxes(q, 1, 2)
        k_ = jnp.swapaxes(k, 1, 2)
        v_ = jnp.swapaxes(v, 1, 2)
        if m:
            out = _chunked_sdpa(q_, k_, v_, is_causal, mask=m[0])
        else:
            out = _flash_sdpa(q_, k_, v_, is_causal)
        return jnp.swapaxes(out, 1, 2)

    args = (query, targ(key), targ(value))
    if attn_mask is not None:
        args = args + (targ(attn_mask),)
    return apply_op("flash_attention_pallas", fn, args)


# ---------------------------------------------------------------------------
# rms_norm
# ---------------------------------------------------------------------------
def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(ms + eps) *
                w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def rms_norm_tpu(x, weight, eps=1e-6, block_rows=512):
    """Row-tiled Pallas RMSNorm (used by the bench path on TPU)."""
    if not (_HAS_PLTPU and _on_tpu()):
        raise RuntimeError("requires TPU")

    def fn(xv, wv):
        shape = xv.shape
        d = shape[-1]
        rows = int(np.prod(shape[:-1]))
        xr = xv.reshape(rows, d)
        br = min(block_rows, rows)
        if rows % br:
            br = rows
        with _x64_off():
            out = pl.pallas_call(
                functools.partial(_rms_kernel, eps=eps),
                grid=(rows // br,),
                in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                          pl.BlockSpec((d,), lambda i: (0,))],
                out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((rows, d), xv.dtype),
            )(xr, wv)
        return out.reshape(shape)

    return apply_op("rms_norm_pallas", fn, (x, targ(weight)))


# ---------------------------------------------------------------------------
# ring attention (sequence/context parallelism over the mesh)
# ---------------------------------------------------------------------------
def _ring_flash_ok(S, D) -> bool:
    """Can the per-rotation block run the Pallas flash kernels?"""
    return (_HAS_PLTPU and (_on_tpu() or _INTERPRET[0])
            and D <= 128 and _fit_block(256, S) > 0)


def _ring_block_fwd(qh, kc, vc, src, idx, causal, hop):
    """One rotation's partial attention via the Pallas flash kernel.

    Global causal structure picks the block kind: hop 0 holds the local
    shard (src == idx statically) -> diagonal causal block, no cond;
    later hops branch at runtime on the device-varying src < idx ->
    fully-visible block vs fully-masked (zero output, -inf lse).
    Returns (o f32 [B,H,S,D], lse f32 [B,H,S])."""
    B, H, S, D = qh.shape
    bq = _fit_block(512, S)
    bk = _fit_block(512, S)

    def _run(c):
        def f():
            o, lse = _flash_attention_value(qh, kc, vc, c, bq, bk,
                                            with_lse=True)
            return o.astype(jnp.float32), lse.reshape(B, H, S)
        return f

    def _empty():
        return (jnp.zeros((B, H, S, D), jnp.float32),
                jnp.full((B, H, S), -jnp.inf, jnp.float32))

    if not causal:
        return _run(False)()
    if hop == 0:
        return _run(True)()
    return lax.cond(src < idx, _run(False), _empty)


def _ring_flash_impl(qh, k0, v0, axis_name, causal):
    """Forward ring: per-rotation flash blocks combined by running
    logsumexp (same online-softmax algebra as inside the kernel, one
    level up)."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    B, H, S, D = qh.shape

    acc = jnp.zeros((B, H, S, D), jnp.float32)
    lse_run = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    kc, vc = k0, v0
    for i in range(n):                      # static unroll over the ring
        src = (idx - i) % n
        o_i, lse_i = _ring_block_fwd(qh, kc, vc, src, idx, causal, i)
        new_lse = jnp.logaddexp(lse_run, lse_i)
        w_old = jnp.where(jnp.isfinite(lse_run),
                          jnp.exp(lse_run - new_lse), 0.0)
        w_new = jnp.where(jnp.isfinite(lse_i),
                          jnp.exp(lse_i - new_lse), 0.0)
        acc = acc * w_old[..., None] + o_i * w_new[..., None]
        lse_run = new_lse
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
    return acc.astype(qh.dtype), lse_run


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(qh, k0, v0, axis_name, causal):
    out, _ = _ring_flash_impl(qh, k0, v0, axis_name, causal)
    return out


def _ring_flash_fwd(qh, k0, v0, axis_name, causal):
    out, lse = _ring_flash_impl(qh, k0, v0, axis_name, causal)
    return out, (qh, k0, v0, out, lse)


def _ring_flash_bwd(axis_name, causal, res, g):
    """Ring backward: each rotation runs the FlashAttention-2 backward
    kernels against the TOTAL out/lse (p recomputed per block is then
    the correct global softmax probability); dk/dv accumulators travel
    around the ring with their k/v shard and arrive home after n hops."""
    qh, k0, v0, out, lse = res
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    B, H, S, D = qh.shape
    lse_c = lse.reshape(B * H, S)
    g = g.astype(out.dtype)

    def _blk(kc, vc, c):
        def f():
            return _flash_bwd_auto(qh, kc, vc, out, lse_c, g, c)
        return f

    def _empty(kc, vc):
        def f():
            return (jnp.zeros_like(qh), jnp.zeros_like(kc),
                    jnp.zeros_like(vc))
        return f

    dq = jnp.zeros((B, H, S, D), jnp.float32)
    kc, vc = k0, v0
    dkc = jnp.zeros_like(k0, jnp.float32)
    dvc = jnp.zeros_like(v0, jnp.float32)
    for i in range(n):
        src = (idx - i) % n
        if not causal:
            dq_i, dk_i, dv_i = _blk(kc, vc, False)()
        elif i == 0:                # hop 0: local shard, statically diag
            dq_i, dk_i, dv_i = _blk(kc, vc, True)()
        else:
            dq_i, dk_i, dv_i = lax.cond(
                src < idx, _blk(kc, vc, False), _empty(kc, vc))
        dq = dq + dq_i.astype(jnp.float32)
        dkc = dkc + dk_i.astype(jnp.float32)
        dvc = dvc + dv_i.astype(jnp.float32)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
    return (dq.astype(qh.dtype), dkc.astype(k0.dtype),
            dvc.astype(v0.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name: str, is_causal=False):
    """Ring attention over a mesh axis (long-context path; SURVEY.md §5.7
    notes the reference LACKS this — sep relied on model-side sharding).

    Must run inside shard_map with the sequence dim sharded over
    ``axis_name``: each step computes a local flash block then rotates k/v
    one neighbor around the ring with collective-permute (rides ICI).
    Inputs [B, S_local, H, D] (values, not Tensors).

    On TPU with kernel-compatible shapes the per-rotation block IS the
    Pallas flash kernel (fwd with lse, FlashAttention-2 bwd against the
    total lse — see _ring_flash); otherwise the einsum online-softmax
    fallback below runs (CPU mesh tests, odd shapes)."""
    # graftlint: waive[trace-shape-branch] -- static kernel dispatch (Pallas flash vs einsum fallback), two variants per shape, not a compile-budget leak
    if _ring_flash_ok(q.shape[1], q.shape[-1]):
        qh_ = jnp.swapaxes(q, 1, 2)
        out = _ring_flash(qh_, jnp.swapaxes(k, 1, 2).astype(qh_.dtype),
                          jnp.swapaxes(v, 1, 2).astype(qh_.dtype),
                          axis_name, is_causal)
        return jnp.swapaxes(out, 1, 2)

    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)  # [B,H,S,D]
    scale = 1.0 / math.sqrt(q.shape[-1])
    B, H, S, D = qh.shape

    # carries are device-varying under shard_map vma checking (jax 0.4.x
    # has no varying-type tracking — identity there)
    def vary(x):
        return jax.lax.pcast(x, (axis_name,), to="varying") \
            if hasattr(jax.lax, "pcast") else x

    m = vary(jnp.full((B, H, S, 1), -jnp.inf, jnp.float32))
    l = vary(jnp.zeros((B, H, S, 1), jnp.float32))
    acc = vary(jnp.zeros((B, H, S, D), jnp.float32))

    kv = (jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2))

    def step(i, carry):
        m, l, acc, (kc, vc) = carry
        src = (idx - i) % n  # which shard's k/v we now hold
        s = jnp.einsum("bhqd,bhkd->bhqk", qh,
                       kc.astype(jnp.float32)) * scale
        if is_causal:
            rows = idx * S + jax.lax.broadcasted_iota(
                jnp.int32, (S, S), 0)
            cols = src * S + jax.lax.broadcasted_iota(
                jnp.int32, (S, S), 1)
            s = jnp.where((rows >= cols)[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, -1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        kc2 = jax.lax.ppermute(kc, axis_name, perm)
        vc2 = jax.lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, (kc2, vc2)

    m, l, acc, _ = jax.lax.fori_loop(0, n, step, (m, l, acc, kv))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def sdpa_ring(query, key, value, mesh, axis_name: str = "sep",
              is_causal: bool = False):
    """Sequence-parallel attention over a mesh axis (SURVEY.md §5.7 —
    the beat-the-reference long-context path; the reference's snapshot
    has NO ring attention).

    q/k/v: [B, S, H, D] with S sharded over ``axis_name``.  Each rank
    computes flash blocks against its local k/v then rotates k/v around
    the ring with collective-permute (ICI); differentiable (the rotation
    loop has a static trip count, so jax.grad reverses it)."""
    from jax.sharding import PartitionSpec as P
    from ..distributed.process_mesh import as_jax_mesh

    jmesh = as_jax_mesh(mesh)

    def _spec_for(shape):
        # all axes are manual under the flash ring (see below), so the
        # batch/head dims must be EXPLICITLY split over the data/fsdp/
        # model axes when present+divisible — P(None, sep) alone would
        # gather and redundantly recompute across those groups
        def axes(names, dim):
            chosen, prod = [], 1
            for name in names:
                sz = jmesh.shape.get(name, 1)
                if sz > 1 and dim % (prod * sz) == 0:
                    chosen.append(name)
                    prod *= sz
            if not chosen:
                return None
            return chosen[0] if len(chosen) == 1 else tuple(chosen)
        return P(axes(("data", "sharding"), shape[0]), axis_name,
                 axes(("model",), shape[2]), None)

    def fn(q, k, v):
        spec = _spec_for(q.shape)
        # check_vma off: pallas_call outputs carry no vma annotation,
        # which the checker (correctly) refuses to guess.  All axes
        # manual (required with the checker off).
        ring = shard_map_compat(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, axis_name,
                                              is_causal),
            jmesh, in_specs=(spec, spec, spec), out_specs=spec)
        return ring(q, k, v)

    return apply_op("ring_attention", fn,
                    (query, targ(key), targ(value)))


def ulysses_attention(q, k, v, axis_name: str, is_causal=False):
    """DeepSpeed-Ulysses attention over a mesh axis (SURVEY.md §5.7 —
    the all-to-all long-context modality; absent from the reference
    snapshot like ring attention).

    Must run inside shard_map with the sequence dim sharded over
    ``axis_name``: an all-to-all trades the sequence shard for a HEAD
    shard (each rank then holds the FULL sequence for H/n heads), local
    full attention runs unsharded, and a second all-to-all restores the
    sequence sharding.  Two all-to-alls ride ICI; compute is exactly the
    dense/flash kernel, so Ulysses wins over ring when heads ≥ ranks and
    the per-rank full sequence fits.  Inputs [B, S_local, H, D]."""
    n = _axis_size(axis_name)
    B, S, H, D = q.shape
    if H % n:
        raise ValueError(f"ulysses needs heads ({H}) divisible by the "
                         f"axis size ({n})")

    def seq_to_heads(x):
        # [B, S_loc, H, D] -> all_to_all -> [B, S_full, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qf = seq_to_heads(q)
    kf = seq_to_heads(k)
    vf = seq_to_heads(v)
    # local attention over the full sequence: [B, H/n, S_full, D] — the
    # Pallas flash kernel when shapes allow (round-4: the einsum/chunked
    # inner step was the VERDICT r3 weak item), chunked fallback otherwise
    qh = jnp.swapaxes(qf, 1, 2)
    kh = jnp.swapaxes(kf, 1, 2)
    vh = jnp.swapaxes(vf, 1, 2)
    # graftlint: waive[trace-shape-branch] -- static kernel dispatch (flash vs chunked fallback), two variants per shape, not a compile-budget leak
    if _ring_flash_ok(qh.shape[2], qh.shape[3]):
        out = _flash_sdpa(qh, kh, vh, is_causal)
    else:
        out = _chunked_sdpa(qh, kh, vh, is_causal)
    out = jnp.swapaxes(out, 1, 2).astype(q.dtype)
    return heads_to_seq(out)


def sdpa_ulysses(query, key, value, mesh, axis_name: str = "sep",
                 is_causal: bool = False):
    """Sequence-parallel attention via Ulysses all-to-all (the companion
    to sdpa_ring; pick ring for S >> heads, ulysses when heads divide
    evenly and all-to-all bandwidth beats n-step rotation).

    q/k/v: [B, S, H, D] with S sharded over ``axis_name``."""
    from jax.sharding import PartitionSpec as P
    from ..distributed.process_mesh import as_jax_mesh

    jmesh = as_jax_mesh(mesh)

    def _spec_for(shape):
        # same all-manual treatment as sdpa_ring (the flash inner path
        # has no vma annotation): batch explicitly split over data/fsdp
        def axes(names, dim):
            chosen, prod = [], 1
            for name in names:
                sz = jmesh.shape.get(name, 1)
                if sz > 1 and dim % (prod * sz) == 0:
                    chosen.append(name)
                    prod *= sz
            if not chosen:
                return None
            return chosen[0] if len(chosen) == 1 else tuple(chosen)
        return P(axes(("data", "sharding"), shape[0]), axis_name,
                 axes(("model",), shape[2]), None)

    def fn(q, k, v):
        spec = _spec_for(q.shape)
        uly = shard_map_compat(
            lambda q_, k_, v_: ulysses_attention(q_, k_, v_, axis_name,
                                                 is_causal),
            jmesh, in_specs=(spec, spec, spec), out_specs=spec)
        return uly(q, k, v)

    return apply_op("ulysses_attention", fn,
                    (query, targ(key), targ(value)))


# ---------------------------------------------------------------------------
# ragged paged attention (serving: one launch for any prefill+decode mix)
# ---------------------------------------------------------------------------
def _ragged_paged_kernel(# scalar prefetch (+2 bitcast scale tables
                         # when quantized), operands (HBM/ANY), output,
                         # scratch — unpacked below
                         *refs,
                         block_size: int, pages_per_span: int,
                         span_q: int, scale: float, groups: int,
                         quantized: bool = False,
                         pipelined: bool = True):
    """Grid cell (s, h): one ragged query SPAN (a decode slot = length-1
    span, or a prefill chunk = length-C span) against one kv head's
    pages (arXiv:2604.15464 "Ragged Paged Attention").

    The packed query batch lives flat on the token axis; each span's
    rows are DMA'd HBM->VMEM as a fixed ``span_q`` window starting at
    its (scalar-prefetched) offset, pages stream through TWO VMEM
    buffers per operand (round 17, ``pipelined=True``): page *i+1*'s
    async copy is issued before attention on page *i* runs, and the
    only stall is the wait at the buffer swap — the TPP pipelining
    argument (arXiv:2104.05755) applied to the page stream.  The
    prefetch is CLAMPED to the span's used block count: page *i+1* is
    fetched only when ``i+1 < n_pages``, so the kernel never reads the
    block table — let alone a page — past what ``kv_len`` covers (the
    r11 poisoned-unused-pages invariant survives the pipeline).
    ``pipelined=False`` keeps the r16 issue-then-wait single-buffer
    loop for old-vs-new benching.  The online-softmax state lives in
    fp32 registers either way, and the output window is DMA'd back.
    Rows past ``q_len`` inside the window compute garbage that the NEXT
    span's cell overwrites (grid order is span-major and sequential),
    so the packed buffer carries ``span_q`` padding rows at the tail
    for the last span's overhang.

    Causality is positional: row r of span s sits at global position
    ``kv_len - q_len + r`` and sees keys at positions <= that, so decode
    steps, mid-prompt chunks, and prefix-hit suffixes are all the same
    span shape to this kernel.

    int8 pools (``quantized=True``): the pages arrive as int8 and the
    per-page-per-head fp32 absmax scales ride as two extra
    scalar-prefetch tables bitcast to int32 ([Hkv, phys] — the same
    SMEM dynamic-index mechanism as the block table).  Pipelined, the
    MXU consumes the int8 codes DIRECTLY: the span's q window is
    quantized once per cell to per-row int8
    (``quantize_rows_symmetric``), ``q·Kᵀ`` runs as an int8×int8
    matmul with int32 accumulate, and ``fold_int8_scores`` folds the
    per-row q scale, the per-page-per-head k scale and the softmax
    scale into the accumulated scores — no fp32 page ever materializes
    in VMEM, so each page buffer is 1/4 the fp32 footprint and the
    matmul runs at the MXU's native int8 rate.  ``p·V`` is int8×int8
    too (probability rows quantized per row, p/v scales folded into
    the [g, D] product — measured ≤1% of value magnitude vs the
    declared 2% tolerance).  The legacy path dequantizes each page
    after its DMA (the r13/r16 behavior), kept under
    ``pipelined=False``.
    """
    from ..quantization.functional import (fold_int8_scores,
                                           quantize_rows_symmetric)
    if quantized:
        (q_off_ref, q_len_ref, kv_len_ref, bt_ref,
         ks_bits_ref, vs_bits_ref,
         q_hbm, k_pages, v_pages, o_hbm,
         q_vmem, o_vmem, k_vmem, v_vmem, sem) = refs
    else:
        (q_off_ref, q_len_ref, kv_len_ref, bt_ref,
         q_hbm, k_pages, v_pages, o_hbm,
         q_vmem, o_vmem, k_vmem, v_vmem, sem) = refs
        ks_bits_ref = vs_bits_ref = None
    s = pl.program_id(0)
    h = pl.program_id(1)
    q_len = q_len_ref[s]
    int8_mxu = quantized and pipelined

    @pl.when(q_len > 0)
    def _span():
        off = q_off_ref[s]
        kv_len = kv_len_ref[s]
        # pipelined, the page slots own sem rows 0/1; the q/o window
        # copies use row 2 (strictly before/after the page loop, so
        # reuse would also be safe — separate rows keep it legible)
        qo_sem = sem.at[2, 0] if pipelined else sem
        cp = pltpu.make_async_copy(
            q_hbm.at[pl.ds(off, span_q), h], q_vmem, qo_sem)
        cp.start()
        cp.wait()
        d = q_vmem.shape[-1]
        g = span_q * groups
        if int8_mxu:
            # one quantization per span window; padded rows are zeros,
            # so the floored per-row scale keeps them zero codes
            q_codes, q_s = quantize_rows_symmetric(
                q_vmem[...].reshape(g, d))
            q = None
        else:
            q = (q_vmem[...].astype(jnp.float32).reshape(g, d)
                 * np.float32(scale))
        # row r of the span (each repeated over its q heads) sits at
        # global position kv_len - q_len + r; garbage rows (r >= q_len)
        # get qpos >= kv_len and attend the whole context — finite,
        # never read
        qpos = (kv_len - q_len + lax.broadcasted_iota(
            jnp.int32, (span_q, groups), 0)).reshape(g, 1)

        m0 = jnp.full((g, 1), _F32_NEG_INF, jnp.float32)
        l0 = jnp.zeros((g, 1), jnp.float32)
        acc0 = jnp.zeros((g, d), jnp.float32)
        n_pages = jnp.minimum(
            (kv_len + jnp.int32(block_size - 1)) // jnp.int32(block_size),
            jnp.int32(pages_per_span))

        def page_math(p_idx, page, kbuf, vbuf, carry):
            """Online-softmax update for one resident page (shared by
            the pipelined and legacy loops; kbuf/vbuf are the page's
            VMEM values, int8 when quantized)."""
            if quantized:
                sk = lax.bitcast_convert_type(ks_bits_ref[h, page],
                                              jnp.float32)
                sv = lax.bitcast_convert_type(vs_bits_ref[h, page],
                                              jnp.float32)
            if int8_mxu:
                si = lax.dot_general(q_codes, kbuf, _DIMNUM_NT,
                                     preferred_element_type=jnp.int32)
                sc = fold_int8_scores(si, q_s, sk, scale)
            else:
                k = kbuf.astype(jnp.float32)           # [bs, D]
                if quantized:
                    k = k * (sk / np.float32(127.0))
                sc = lax.dot_general(q, k, _DIMNUM_NT,
                                     preferred_element_type=jnp.float32)
            base = p_idx * jnp.int32(block_size)
            cols = base + lax.broadcasted_iota(
                jnp.int32, (g, block_size), 1)
            ok = (cols <= qpos) & (cols < kv_len)
            sc = jnp.where(ok, sc, _F32_NEG_INF)

            def pv_of_p(p):
                if int8_mxu:
                    # p·V runs int8×int8 too: the probability rows are
                    # quantized per row (max p per row is the scale)
                    # and the p/v scales fold into the [g, d] product —
                    # the page NEVER materializes in fp32 (measured
                    # ≤1% of value magnitude vs the declared 2%
                    # tolerance)
                    p_codes, p_s = quantize_rows_symmetric(p)
                    pvi = lax.dot_general(
                        p_codes, vbuf, _DIMNUM_NN,
                        preferred_element_type=jnp.int32)
                    return fold_int8_scores(pvi, p_s, sv)
                v = vbuf.astype(jnp.float32)
                if quantized:
                    v = v * (sv / np.float32(127.0))
                return lax.dot_general(p, v, _DIMNUM_NN,
                                       preferred_element_type=jnp.float32)

            return online_softmax_update(carry, sc, ok, pv_of_p)

        if pipelined:
            def start_page(p_idx, slot):
                page = bt_ref[s, p_idx]
                pltpu.make_async_copy(k_pages.at[h, page],
                                      k_vmem.at[slot],
                                      sem.at[slot, 0]).start()
                pltpu.make_async_copy(v_pages.at[h, page],
                                      v_vmem.at[slot],
                                      sem.at[slot, 1]).start()

            def wait_page(p_idx, slot):
                page = bt_ref[s, p_idx]
                pltpu.make_async_copy(k_pages.at[h, page],
                                      k_vmem.at[slot],
                                      sem.at[slot, 0]).wait()
                pltpu.make_async_copy(v_pages.at[h, page],
                                      v_vmem.at[slot],
                                      sem.at[slot, 1]).wait()

            @pl.when(n_pages > 0)
            def _warm():
                start_page(jnp.int32(0), jnp.int32(0))

            def body(p_idx, carry):
                slot = lax.rem(p_idx, jnp.int32(2))
                # prefetch clamp: the last used page issues NO copy —
                # bt_ref[s, n_pages] (and anything past the span's
                # block count) is never read
                @pl.when(p_idx + 1 < n_pages)
                def _prefetch():
                    start_page(p_idx + 1, jnp.int32(1) - slot)
                wait_page(p_idx, slot)
                return page_math(p_idx, bt_ref[s, p_idx],
                                 k_vmem[slot], v_vmem[slot], carry)
        else:
            def body(p_idx, carry):
                page = bt_ref[s, p_idx]
                kc = pltpu.make_async_copy(k_pages.at[h, page], k_vmem,
                                           sem)
                kc.start()
                kc.wait()
                vc = pltpu.make_async_copy(v_pages.at[h, page], v_vmem,
                                           sem)
                vc.start()
                vc.wait()
                return page_math(p_idx, page, k_vmem[...], v_vmem[...],
                                 carry)

        m, l, acc = lax.fori_loop(jnp.int32(0), n_pages, body,
                                  (m0, l0, acc0))
        o_vmem[...] = (acc / jnp.maximum(l, np.float32(1e-30))).reshape(
            span_q, groups, d).astype(o_vmem.dtype)
        op = pltpu.make_async_copy(
            o_vmem, o_hbm.at[pl.ds(off, span_q), h],
            sem.at[2, 1] if pipelined else sem)
        op.start()
        op.wait()


def _ragged_paged_attention_pallas(q, key_cache, value_cache,
                                   block_tables, q_offsets, q_lens,
                                   kv_lens, scale, span_q: int,
                                   interpret=False,
                                   key_scale=None, value_scale=None,
                                   pipelined: bool = True):
    """q: [T, H, D] packed ragged tokens; block_tables [S, W]; span
    tables [S].  span_q: static max span length (>= max(q_lens)).
    Returns [T, H, D].

    Head sharding (tensor-parallel serving): the kernel is
    shard-oblivious — every head index here is LOCAL.  Each chip calls
    it with its own head shard (H/tp queries, Hkv/tp kv heads) against
    its head shard of every page, the grid is (span, local_kv_head),
    and no global head id ever appears, so the same kernel serves
    single-chip and per-chip-shard launches without index plumbing.
    The only cross-shard invariant is that the GQA group size H/Hkv
    survives the shard (both divide by tp) — checked below.
    """
    T, H, D = q.shape
    Hkv = key_cache.shape[2]
    bs = key_cache.shape[1]
    if Hkv <= 0 or H % Hkv:
        raise ValueError(
            "ragged paged attention: %d query heads do not group over "
            "%d kv heads — under tensor parallelism shard both by the "
            "same tp degree so the GQA group size is preserved"
            % (H, Hkv))
    groups = H // Hkv
    S, W = block_tables.shape
    span_q = max(1, int(span_q))
    quantized = key_scale is not None
    qg = q.reshape(T, Hkv, groups, D).astype(jnp.float32)
    # span_q tail padding: the last span's fixed DMA window may overhang
    qg = jnp.pad(qg, ((0, span_q), (0, 0), (0, 0), (0, 0)))
    kp = jnp.moveaxis(key_cache, 2, 0)
    vp = jnp.moveaxis(value_cache, 2, 0)
    if not quantized:
        kp, vp = kp.astype(jnp.float32), vp.astype(jnp.float32)
    bt = jnp.maximum(block_tables, 0)

    kernel = functools.partial(
        _ragged_paged_kernel, block_size=bs, pages_per_span=W,
        span_q=span_q, scale=scale, groups=groups, quantized=quantized,
        pipelined=pipelined)
    if pipelined:
        # double-buffered page stream: 2 VMEM slots per operand, one
        # DMA sem row per slot (k col 0 / v col 1) + a q/o row
        page_scratch = [pltpu.VMEM((2, bs, D), kp.dtype),
                        pltpu.VMEM((2, bs, D), vp.dtype),
                        pltpu.SemaphoreType.DMA((3, 2))]
    else:
        page_scratch = [pltpu.VMEM((bs, D), kp.dtype),
                        pltpu.VMEM((bs, D), vp.dtype),
                        pltpu.SemaphoreType.DMA]

    with _x64_off():
        prefetch = [q_offsets.astype(jnp.int32), q_lens.astype(jnp.int32),
                    kv_lens.astype(jnp.int32), bt.astype(jnp.int32)]
        if quantized:
            # fp32 scales ride the int32 scalar-prefetch lane bitcast;
            # [phys, Hkv] -> [Hkv, phys] so the kernel indexes [h, page]
            prefetch += [
                jax.lax.bitcast_convert_type(
                    key_scale.astype(jnp.float32).T, jnp.int32),
                jax.lax.bitcast_convert_type(
                    value_scale.astype(jnp.float32).T, jnp.int32)]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(prefetch),
            grid=(S, Hkv),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
                pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            scratch_shapes=[
                pltpu.VMEM((span_q, groups, D), jnp.float32),
                pltpu.VMEM((span_q, groups, D), q.dtype),
            ] + page_scratch,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((T + span_q, Hkv, groups, D),
                                           q.dtype),
            interpret=interpret,
        )(*prefetch, qg, kp, vp)
    return out[:T].reshape(T, H, D)


# ---------------------------------------------------------------------------
# fused RoPE + QKV epilogue (serving: one HBM round trip per layer's
# pre-attention transforms instead of three)
# ---------------------------------------------------------------------------
def rope_tables_for_positions(positions, dim, base=10000.0):
    """Neox cos/sin tables for a TOKEN-INDEXED position vector:
    positions [N] int32 (each token's GLOBAL position) -> (cos, sin)
    [N, dim] f32.  Bit-identical to the tables
    ``incubate.nn.functional.fused_rotary_position_embedding`` builds
    from ``position_ids`` (same inv-frequency expression, same f32
    order of operations), so swapping the serving steps onto the fused
    epilogue keeps fp32 engines byte-identical end-to-end.  Traceable;
    the serving steps call it ONCE per step and reuse the tables across
    every layer (the per-layer rebuild was pure waste — positions do
    not change between layers)."""
    inv = 1.0 / (base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    freqs = positions.astype(jnp.float32)[:, None] * inv[None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def _rope_rows(t, cos, sin):
    """Neox rotation of token-major rows: t [N, Hx, D] x cos/sin [N, D]
    (broadcast over the head axis).  The SAME op order as
    ``fused_rotary_position_embedding``'s rope_one, so the values are
    bit-identical; shared by the XLA reference and the kernel body."""
    tf = t.astype(jnp.float32)
    half = tf.shape[-1] // 2
    rot = jnp.concatenate([-tf[..., half:], tf[..., :half]], axis=-1)
    return tf * cos[:, None, :] + rot * sin[:, None, :]


def _rope_qkv_kernel(*refs, with_amax: bool):
    """One row tile of the fused pre-attention epilogue: rope(q),
    rope(k), and (quantized pools) the per-token per-head K/V absmax
    rows the quantize-on-write scatter needs — one read of the
    projection outputs and one write, where the graph-level path cost
    a rope pass over q, a rope pass over k, and an abs-max pass over
    k/v (three HBM round trips of the same data)."""
    if with_amax:
        (q_ref, k_ref, v_ref, cos_ref, sin_ref,
         qo_ref, ko_ref, ka_ref, va_ref) = refs
    else:
        q_ref, k_ref, cos_ref, sin_ref, qo_ref, ko_ref = refs
        v_ref = ka_ref = va_ref = None
    cos = cos_ref[...]
    sin = sin_ref[...]
    qo_ref[...] = _rope_rows(q_ref[...], cos, sin).astype(qo_ref.dtype)
    ko = _rope_rows(k_ref[...], cos, sin).astype(ko_ref.dtype)
    ko_ref[...] = ko
    if with_amax:
        # absmax of the STORED values (post-cast), bit-matching what
        # _quant_write_tokens would recompute from the scattered rows
        ka_ref[...] = jnp.max(jnp.abs(ko.astype(jnp.float32)), axis=-1)
        va_ref[...] = jnp.max(jnp.abs(v_ref[...].astype(jnp.float32)),
                              axis=-1)


def _rope_qkv_epilogue_xla(q, k, v, cos, sin, with_amax):
    """Graph-level reference (CPU serving path + parity tests): the
    exact same f32 expressions as the kernel, so interpret-vs-XLA
    parity is byte-level and the CPU engines keep their end-to-end
    byte identity with eager generate."""
    q_rot = _rope_rows(q, cos, sin).astype(q.dtype)
    k_rot = _rope_rows(k, cos, sin).astype(k.dtype)
    if not with_amax:
        return q_rot, k_rot, None, None
    k_amax = jnp.max(jnp.abs(k_rot.astype(jnp.float32)), axis=-1)
    v_amax = jnp.max(jnp.abs(v.astype(jnp.float32)), axis=-1)
    return q_rot, k_rot, k_amax, v_amax


def _rope_epilogue_tile(heads: int, head_dim: int, itemsize: int,
                        cap_rows: int = 512) -> int:
    """Row-tile chooser shared by the epilogue wrapper and the VMEM
    audit: the widest operand's tile stays under ~1 MiB so the kernel
    fits the 16 MiB serving budget at any head count (64 q heads ×
    D=128 would need 109 MiB at a fixed 512-row tile — the audit
    caught exactly that)."""
    cap = max(1, (1 << 20) // max(1, heads * head_dim * itemsize))
    tile = min(cap_rows, cap)
    if tile > 8:
        tile = (tile // 8) * 8
    return max(1, tile)


def rope_qkv_epilogue(q, k, v, cos, sin, with_amax: bool = False,
                      use_pallas=None, interpret=False, block_rows=512):
    """Fused pre-attention epilogue for the serving steps (round 17).

    q: [N, H, D], k/v: [N, Hkv, D] token-major projection outputs;
    cos/sin: [N, D] from :func:`rope_tables_for_positions`.  Applies
    neox RoPE to q and k at each token's global position and, for int8
    KV pools (``with_amax``), also emits the per-token per-head K/V
    absmax rows consumed by the quantize-on-write scatter — ONE Pallas
    pass over the projection outputs on TPU, replacing the separate
    rope-q / rope-k / absmax graph passes.  v itself is returned
    untouched by the caller (never copied here).

    Returns ``(q_rot, k_rot, k_amax, v_amax)`` (amaxes None unless
    ``with_amax``).  The XLA fallback is bit-identical to the kernel's
    math, so CPU dryrun engines stay byte-identical end-to-end.
    """
    if use_pallas is None:
        use_pallas = _HAS_PLTPU and _on_tpu()
    if not (use_pallas or interpret):
        return _rope_qkv_epilogue_xla(q, k, v, cos, sin, with_amax)

    N, H, D = q.shape
    Hkv = k.shape[1]
    tile = min(_rope_epilogue_tile(H, D, q.dtype.itemsize, block_rows),
               N)
    pad = (-N) % tile
    if pad:
        widths = ((0, pad), (0, 0), (0, 0))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        if with_amax:
            v = jnp.pad(v, widths)
        cos = jnp.pad(cos, ((0, pad), (0, 0)))
        sin = jnp.pad(sin, ((0, pad), (0, 0)))
    rows = N + pad

    def spec(hx):
        return pl.BlockSpec((tile, hx, D), lambda i: (i, 0, 0))

    cs_spec = pl.BlockSpec((tile, D), lambda i: (i, 0))
    amax_spec = pl.BlockSpec((tile, Hkv), lambda i: (i, 0))
    in_specs = [spec(H), spec(Hkv)]
    args = [q, k]
    if with_amax:
        in_specs.append(spec(Hkv))
        args.append(v)
    in_specs += [cs_spec, cs_spec]
    args += [cos, sin]
    out_specs = [spec(H), spec(Hkv)]
    out_shape = [jax.ShapeDtypeStruct((rows, H, D), q.dtype),
                 jax.ShapeDtypeStruct((rows, Hkv, D), k.dtype)]
    if with_amax:
        out_specs += [amax_spec, amax_spec]
        out_shape += [jax.ShapeDtypeStruct((rows, Hkv), jnp.float32),
                      jax.ShapeDtypeStruct((rows, Hkv), jnp.float32)]

    with _x64_off():
        res = pl.pallas_call(
            functools.partial(_rope_qkv_kernel, with_amax=with_amax),
            grid=(rows // tile,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(*args)
    q_rot, k_rot = res[0][:N], res[1][:N]
    if with_amax:
        return q_rot, k_rot, res[2][:N], res[3][:N]
    return q_rot, k_rot, None, None


# ---------------------------------------------------------------------------
# VMEM footprint audit (consumed by tools/check_vmem_budget.py)
# ---------------------------------------------------------------------------
# Mosaic tiles every VMEM-resident buffer to (sublane, 128) vregs; the
# sublane count depends on itemsize (f32: 8, bf16: 16, int8: 32).  The
# audit pads every tile the way the hardware will, so a "small" [g, 1]
# running-max column is honestly counted as the [g, 128] lane broadcast
# it occupies on silicon.
_VMEM_LANE = 128


def _tile_bytes(shape, itemsize: int) -> int:
    """Lane/sublane-padded bytes of one VMEM-resident tile."""
    shape = tuple(int(s) for s in shape)
    if not shape:
        shape = (1, 1)
    elif len(shape) == 1:
        shape = (1,) + shape
    sub = 8 * (4 // max(1, min(itemsize, 4)))  # f32:8, bf16:16, int8:32
    lead = 1
    for s in shape[:-2]:
        lead *= s
    rows = -(-shape[-2] // sub) * sub
    cols = -(-shape[-1] // _VMEM_LANE) * _VMEM_LANE
    return lead * rows * cols * itemsize


def ragged_kernel_vmem_bytes(*, span_q: int, groups: int, head_dim: int,
                             block_size: int, q_itemsize: int = 4,
                             kv_itemsize: int = 4, pipelined: bool = True,
                             quantized: bool = False) -> int:
    """Worst-case VMEM bytes of ONE _ragged_paged_kernel grid cell:
    the span_q query window (f32 scratch + output-dtype staging), the
    page buffers (×2 per operand when pipelined — the round-17 double
    buffering), and the live compute tiles (online-softmax m/l/acc,
    the [g, block_size] score/probability tile, and the int8 q codes
    + per-row scales on the quantized MXU path).  Mirrors the
    scratch_shapes in _ragged_paged_attention_pallas — edit both or
    tools/check_vmem_budget.py fails."""
    g = span_q * groups
    d = head_dim
    bufs = 2 if pipelined else 1
    total = _tile_bytes((span_q, groups, d), 4)           # q window f32
    total += _tile_bytes((span_q, groups, d), q_itemsize)  # o staging
    total += 2 * bufs * _tile_bytes((block_size, d), kv_itemsize)  # k+v
    total += _tile_bytes((g, d), 4)                       # acc
    total += 2 * _tile_bytes((g, 1), 4)                   # m, l
    total += 2 * _tile_bytes((g, block_size), 4)          # scores + p
    if quantized and pipelined:
        total += _tile_bytes((g, d), 1)                   # q int8 codes
        total += _tile_bytes((g, 1), 4)                   # q row scales
        total += _tile_bytes((g, block_size), 4)          # i32 scores
    return total


def decode_kernel_vmem_bytes(*, groups: int, head_dim: int,
                             block_size: int, q_itemsize: int = 4,
                             kv_itemsize: int = 4, pipelined: bool = True,
                             quantized: bool = False) -> int:
    """Worst-case VMEM bytes of ONE _paged_decode_kernel grid cell.
    The q/o operands are BlockSpec-streamed (Mosaic double-buffers
    them: ×2); pages go through the manual 2-slot DMA buffers."""
    return ragged_kernel_vmem_bytes(
        span_q=1, groups=groups, head_dim=head_dim,
        block_size=block_size, q_itemsize=q_itemsize,
        kv_itemsize=kv_itemsize, pipelined=pipelined,
        quantized=quantized) \
        + _tile_bytes((groups, head_dim), q_itemsize) * 2  # q+o 2nd buf


def rope_epilogue_vmem_bytes(*, heads: int, kv_heads: int,
                             head_dim: int, itemsize: int = 4,
                             with_amax: bool = True) -> int:
    """One _rope_qkv_kernel row tile: q/k (+v) in, q/k (+amax) out —
    every operand BlockSpec-streamed, so ×2 for Mosaic's pipeline —
    plus the f32 rotation temporaries for the widest operand.  Rows
    come from the SAME chooser the wrapper uses, so a tile-cap edit is
    audited automatically."""
    rows = _rope_epilogue_tile(heads, head_dim, itemsize)
    per_buf = (_tile_bytes((rows, heads, head_dim), itemsize)
               + _tile_bytes((rows, kv_heads, head_dim), itemsize))
    n_v = _tile_bytes((rows, kv_heads, head_dim), itemsize) \
        if with_amax else 0
    amax = 2 * _tile_bytes((rows, kv_heads), 4) if with_amax else 0
    rot = 2 * _tile_bytes((rows, heads, head_dim), 4)     # tf + rot f32
    return 2 * (2 * per_buf + n_v + amax) + rot


def flash_fwd_vmem_bytes(*, block_q: int, block_k: int, head_dim: int,
                         itemsize: int = 4, with_lse: bool = True,
                         with_rope: bool = False) -> int:
    """One _flash_fwd_kernel grid cell: BlockSpec-streamed q/k/v/out
    (×2 each), the m/l/acc/qs scratch, and the [bq, bk] score tile."""
    d = head_dim
    blocks = _tile_bytes((block_q, d), itemsize) * 2 \
        + 2 * _tile_bytes((block_k, d), itemsize) * 2 \
        + _tile_bytes((block_q, d), itemsize) * 2            # out
    if with_lse:
        blocks += _tile_bytes((block_q, _VMEM_LANE), 4) * 2
    if with_rope:
        blocks += 4 * _tile_bytes((max(block_q, block_k), d), 4) * 2
    scratch = 2 * _tile_bytes((block_q, _VMEM_LANE), 4) \
        + _tile_bytes((block_q, d), 4) \
        + _tile_bytes((block_q, d), itemsize)
    tiles = 2 * _tile_bytes((block_q, block_k), 4)           # s + p
    return blocks + scratch + tiles


def flash_bwd_fused_vmem_bytes(*, block_q: int, block_k: int,
                               head_dim: int, itemsize: int = 4,
                               with_rope: bool = False) -> int:
    """One _flash_bwd_kv_kernel (emit_dq) grid cell — the largest
    kernel in the tree: streamed q/o/do/lse blocks, resident k/v
    blocks, dq/dk/dv outputs, dk/dv/ks scratch, and the [bq, bk]
    p/ds/dp tiles."""
    d = head_dim
    blocks = 3 * _tile_bytes((block_q, d), itemsize) * 2 \
        + _tile_bytes((block_q, _VMEM_LANE), 4) * 2 \
        + 2 * _tile_bytes((block_k, d), itemsize) * 2 \
        + _tile_bytes((block_q, d), 4) * 2 \
        + 2 * _tile_bytes((block_k, d), itemsize) * 2
    if with_rope:
        blocks += 4 * _tile_bytes((max(block_q, block_k), d), 4) * 2
    scratch = 2 * _tile_bytes((block_k, d), 4) \
        + _tile_bytes((block_k, d), itemsize)
    tiles = 3 * _tile_bytes((block_q, block_k), 4)           # p, dp, ds
    return blocks + scratch + tiles


def kernel_vmem_report(envelope=None):
    """name -> worst-case per-core VMEM bytes for every Pallas kernel
    family, at the declared serving/training ENVELOPE (the largest
    configuration the repo's engines and benches actually launch).
    tools/check_vmem_budget.py gates this against the per-core budget;
    grow the envelope here FIRST when a new config is introduced."""
    env = {
        # serving envelope: the TPU bench line (bench_serving.py) —
        # chunk/span_q 256, 16-token pages, head_dim 128, and GQA
        # grouping up to 8 q heads per kv head
        "span_q": 256, "groups": 8, "head_dim": 128, "block_size": 16,
        # training envelope: the default/autotuned flash tiles
        "block_q": 512, "block_k": 512,
        "bwd_block_q": _FUSED_BWD_BLOCK_Q,
        "bwd_block_k": _FUSED_BWD_MAX_SK // 4,
    }
    if envelope:
        env.update(envelope)
    return {
        "ragged_paged_fp32": ragged_kernel_vmem_bytes(
            span_q=env["span_q"], groups=env["groups"],
            head_dim=env["head_dim"], block_size=env["block_size"]),
        "ragged_paged_int8": ragged_kernel_vmem_bytes(
            span_q=env["span_q"], groups=env["groups"],
            head_dim=env["head_dim"], block_size=env["block_size"],
            kv_itemsize=1, quantized=True),
        "paged_decode_fp32": decode_kernel_vmem_bytes(
            groups=env["groups"], head_dim=env["head_dim"],
            block_size=env["block_size"]),
        "paged_decode_int8": decode_kernel_vmem_bytes(
            groups=env["groups"], head_dim=env["head_dim"],
            block_size=env["block_size"], kv_itemsize=1,
            quantized=True),
        "rope_qkv_epilogue": rope_epilogue_vmem_bytes(
            heads=8 * env["groups"], kv_heads=env["groups"],
            head_dim=env["head_dim"]),
        "flash_fwd": flash_fwd_vmem_bytes(
            block_q=env["block_q"], block_k=env["block_k"],
            head_dim=env["head_dim"], with_rope=True),
        "flash_bwd_fused": flash_bwd_fused_vmem_bytes(
            block_q=env["bwd_block_q"], block_k=env["bwd_block_k"],
            head_dim=env["head_dim"], with_rope=True),
    }
