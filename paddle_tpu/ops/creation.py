"""Tensor creation ops.

Parity: python/paddle/tensor/creation.py (reference), backed by phi full/...
kernels.  Here creation is jnp array construction placed via the current
Place (PJRT device).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor, to_tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap, unwrap, targ


def _dtype_or_default(dtype):
    return _dt.convert_dtype(dtype) if dtype is not None \
        else _dt.get_default_dtype()


@register_op("zeros", category="creation")
def zeros(shape, dtype=None, name=None):
    return wrap(jnp.zeros(_shape(shape), _dtype_or_default(dtype)))


@register_op("ones", category="creation")
def ones(shape, dtype=None, name=None):
    return wrap(jnp.ones(_shape(shape), _dtype_or_default(dtype)))


@register_op("full", category="creation")
def full(shape, fill_value, dtype=None, name=None):
    if dtype is None:
        val = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
        if isinstance(val, bool):
            d = np.dtype(bool)
        elif isinstance(val, int):
            d = np.dtype(np.int64)
        else:
            d = _dt.get_default_dtype()
    else:
        d = _dt.convert_dtype(dtype)
    fv = fill_value.item() if isinstance(fill_value, Tensor) else fill_value
    return wrap(jnp.full(_shape(shape), fv, d))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in shape)


@register_op("zeros_like", category="creation", tensor_method=True)
def zeros_like(x, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return wrap(jnp.zeros_like(as_value(x), dtype=d))


@register_op("ones_like", category="creation", tensor_method=True)
def ones_like(x, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return wrap(jnp.ones_like(as_value(x), dtype=d))


@register_op("full_like", category="creation", tensor_method=True)
def full_like(x, fill_value, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return wrap(jnp.full_like(as_value(x), fill_value, dtype=d))


@register_op("empty", category="creation")
def empty(shape, dtype=None, name=None):
    return wrap(jnp.zeros(_shape(shape), _dtype_or_default(dtype)))


@register_op("empty_like", category="creation")
def empty_like(x, dtype=None, name=None):
    return wrap(jnp.zeros_like(as_value(x), dtype=_dt.convert_dtype(dtype)))


@register_op("arange", category="creation")
def arange(start=0, end=None, step=1, dtype=None, name=None):
    s = start.item() if isinstance(start, Tensor) else start
    e = end.item() if isinstance(end, Tensor) else end
    st = step.item() if isinstance(step, Tensor) else step
    if e is None:
        s, e = 0, s
    if dtype is None:
        dtype = np.int64 if all(
            isinstance(v, (int, np.integer)) for v in (s, e, st)) \
            else _dt.get_default_dtype()
    return wrap(jnp.arange(s, e, st, _dt.convert_dtype(dtype)))


@register_op("linspace", category="creation")
def linspace(start, stop, num, dtype=None, name=None):
    return wrap(jnp.linspace(unwrap(start), unwrap(stop), int(num),
                             dtype=_dt.convert_dtype(dtype)))


@register_op("logspace", category="creation")
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return wrap(jnp.logspace(unwrap(start), unwrap(stop), int(num),
                             base=base, dtype=_dt.convert_dtype(dtype)))


@register_op("eye", category="creation")
def eye(num_rows, num_columns=None, dtype=None, name=None):
    return wrap(jnp.eye(num_rows, num_columns,
                        dtype=_dtype_or_default(dtype)))


@register_op("meshgrid", category="creation")
def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply_op("meshgrid",
                    lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                    args)
    return list(outs)


@register_op("diag", category="creation", tensor_method=True)
def diag(x, offset=0, padding_value=0, name=None):
    def fn(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, v.dtype)
            return out + jnp.diag(v, offset) - jnp.diag(
                jnp.full((v.shape[0],), padding_value, v.dtype), offset)
        return jnp.diag(v, offset)
    return apply_op("diag", fn, (x,))


@register_op("diagflat", category="creation", tensor_method=True)
def diagflat(x, offset=0, name=None):
    return apply_op("diagflat", lambda v: jnp.diagflat(v, offset), (x,))


@register_op("diag_embed", category="creation")
def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def fn(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        base = base.at[..., r, c].set(v)
        perm_needed = (dim1, dim2) != (-2, -1)
        if perm_needed:
            base = jnp.moveaxis(base, (-2, -1), (dim1, dim2))
        return base
    return apply_op("diag_embed", fn, (x,))


@register_op("diagonal", category="creation", tensor_method=True)
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal",
                    lambda v: jnp.diagonal(v, offset, axis1, axis2), (x,))


@register_op("tril", category="creation", tensor_method=True)
def tril(x, diagonal=0, name=None):
    return apply_op("tril", lambda v: jnp.tril(v, diagonal), (x,))


@register_op("triu", category="creation", tensor_method=True)
def triu(x, diagonal=0, name=None):
    return apply_op("triu", lambda v: jnp.triu(v, diagonal), (x,))


@register_op("tril_indices", category="creation")
def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(row, offset, col)
    return wrap(jnp.asarray(np.stack([r, c]), _dt.convert_dtype(dtype)))


@register_op("triu_indices", category="creation")
def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return wrap(jnp.asarray(np.stack([r, c]), _dt.convert_dtype(dtype)))


@register_op("assign", category="creation")
def assign(x, output=None, name=None):
    val = as_value(x)
    if output is not None:
        output.set_value(val)
        return output
    return apply_op("assign", lambda v: v + 0 if jnp.issubdtype(
        v.dtype, jnp.inexact) else v, (x,)) if isinstance(x, Tensor) \
        else wrap(val)


@register_op("numel", category="creation", tensor_method=False)
def numel(x, name=None):
    return wrap(jnp.asarray(int(np.prod(as_value(x).shape)), jnp.int64))


@register_op("one_hot", category="creation")
def one_hot(x, num_classes, name=None):
    return apply_op(
        "one_hot",
        lambda v: jax.nn.one_hot(v, num_classes,
                                 dtype=_dt.get_default_dtype()), (x,))


@register_op("complex", category="creation")
def complex(real, imag, name=None):
    return apply_op("complex", jax.lax.complex, (real, targ(imag)))


@register_op("as_complex", category="creation", tensor_method=True)
def as_complex(x, name=None):
    return apply_op("as_complex",
                    lambda v: jax.lax.complex(v[..., 0], v[..., 1]), (x,))


@register_op("as_real", category="creation", tensor_method=True)
def as_real(x, name=None):
    return apply_op("as_real",
                    lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], -1), (x,))


@register_op("clone", category="creation", tensor_method=True)
def clone(x, name=None):
    return apply_op("clone", lambda v: v + 0 if jnp.issubdtype(
        v.dtype, jnp.inexact) else v, (x,))


@register_op("cast", category="creation")
def cast(x, dtype, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op("cast", lambda v: v.astype(d), (x,))
