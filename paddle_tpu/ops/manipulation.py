"""Shape/layout manipulation + indexing ops.

Parity: python/paddle/tensor/manipulation.py (reference), phi kernels
reshape/transpose/concat/gather/scatter/....  All lower to XLA reshape /
transpose / gather / scatter HLO — static shapes keep the MXU tiling happy.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap, unwrap, targ


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


@register_op("reshape", category="manipulation", tensor_method=True,
             inplace_alias=True)
def reshape(x, shape, name=None):
    shp = _static_shape(shape)
    return apply_op("reshape", lambda v: jnp.reshape(v, shp), (x,))


view = reshape
register("view", reshape, category="manipulation", tensor_method=True,
         method_name="view")


@register_op("transpose", category="manipulation", tensor_method=True)
def transpose(x, perm=None, name=None):
    if perm is None:
        perm = list(range(as_value(x).ndim))[::-1]
    perm = tuple(int(p) for p in perm)
    return apply_op("transpose", lambda v: jnp.transpose(v, perm), (x,))


@register_op("moveaxis", category="manipulation", tensor_method=True)
def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis",
                    lambda v: jnp.moveaxis(v, source, destination), (x,))


@register_op("swapaxes", category="manipulation", tensor_method=True)
def swapaxes(x, axis1, axis2, name=None):
    return apply_op("swapaxes",
                    lambda v: jnp.swapaxes(v, axis1, axis2), (x,))


register("swapdims", swapaxes, tensor_method=True, method_name="swapdims")


@register_op("flatten", category="manipulation", tensor_method=True,
             inplace_alias=True)
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(v):
        nd = v.ndim
        if nd == 0:
            return v.reshape(1)
        s = start_axis % nd
        e = stop_axis % nd
        shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return v.reshape(shape)
    return apply_op("flatten", fn, (x,))


@register_op("squeeze", category="manipulation", tensor_method=True,
             inplace_alias=True)
def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % v.ndim for a in ax if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, ax) if ax else v
    return apply_op("squeeze", fn, (x,))


@register_op("unsqueeze", category="manipulation", tensor_method=True,
             inplace_alias=True)
def unsqueeze(x, axis, name=None):
    def fn(v):
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        out = v
        for a in sorted(int(unwrap(a)) if isinstance(a, Tensor) else int(a)
                        for a in ax):
            out = jnp.expand_dims(out, a)
        return out
    return apply_op("unsqueeze", fn, (x,))


@register_op("concat", category="manipulation")
def concat(x, axis=0, name=None):
    ts = tuple(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op("concat", lambda *vs: jnp.concatenate(vs, ax), ts)


register("concatenate", concat)


@register_op("stack", category="manipulation")
def stack(x, axis=0, name=None):
    ts = tuple(x)
    return apply_op("stack", lambda *vs: jnp.stack(vs, int(axis)), ts)


@register_op("split", category="manipulation", tensor_method=True)
def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    v = as_value(x)
    n = v.shape[ax]
    if isinstance(num_or_sections, int):
        if n % num_or_sections != 0:
            raise ValueError(
                f"split: axis dim {n} is not divisible by "
                f"num_or_sections={num_or_sections}")
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            sizes[neg[0]] = n - sum(s for s in sizes if s >= 0)
    idx = np.cumsum(sizes)[:-1].tolist()
    outs = apply_op("split",
                    lambda v: tuple(jnp.split(v, idx, ax)), (x,))
    return list(outs)


@register_op("chunk", category="manipulation", tensor_method=True)
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


@register_op("unbind", category="manipulation", tensor_method=True)
def unbind(x, axis=0, name=None):
    v = as_value(x)
    n = v.shape[axis]
    outs = apply_op(
        "unbind",
        lambda v: tuple(jnp.squeeze(s, axis)
                        for s in jnp.split(v, n, axis)), (x,))
    return list(outs)


register("unstack", unbind)


@register_op("tile", category="manipulation", tensor_method=True)
def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply_op("tile", lambda v: jnp.tile(v, reps), (x,))


@register_op("expand", category="manipulation", tensor_method=True)
def expand(x, shape, name=None):
    shp = _static_shape(shape)
    def fn(v):
        tgt = tuple(v.shape[i - (len(shp) - v.ndim)] if s == -1 else s
                    for i, s in enumerate(shp))
        return jnp.broadcast_to(v, tgt)
    return apply_op("expand", fn, (x,))


@register_op("expand_as", category="manipulation", tensor_method=True)
def expand_as(x, y, name=None):
    tgt = tuple(as_value(y).shape)
    return apply_op("expand_as", lambda v: jnp.broadcast_to(v, tgt), (x,))


@register_op("broadcast_to", category="manipulation", tensor_method=True)
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


@register_op("broadcast_tensors", category="manipulation")
def broadcast_tensors(inputs, name=None):
    outs = apply_op("broadcast_tensors",
                    lambda *vs: tuple(jnp.broadcast_arrays(*vs)),
                    tuple(inputs))
    return list(outs)


@register_op("flip", category="manipulation", tensor_method=True)
def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op("flip", lambda v: jnp.flip(v, ax), (x,))


@register_op("rot90", category="manipulation", tensor_method=True)
def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda v: jnp.rot90(v, k, axes), (x,))


@register_op("roll", category="manipulation", tensor_method=True)
def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda v: jnp.roll(v, shifts, axis), (x,))


@register_op("gather", category="manipulation", tensor_method=True)
def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    def fn(v, idx):
        idx = idx.reshape(-1) if idx.ndim > 1 else idx
        return jnp.take(v, idx, axis=ax)
    return apply_op("gather", fn, (x, targ(index)))


@register_op("gather_nd", category="manipulation", tensor_method=True)
def gather_nd(x, index, name=None):
    def fn(v, idx):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply_op("gather_nd", fn, (x, targ(index)))


@register_op("scatter", category="manipulation", tensor_method=True,
             inplace_alias=True)
def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, idx, upd):
        idx = idx.reshape(-1)
        if overwrite:
            return v.at[idx].set(upd)
        # paddle semantics: zero the rows then accumulate
        zeroed = v.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return apply_op("scatter", fn, (x, targ(index), targ(updates)))


@register_op("scatter_nd_add", category="manipulation", tensor_method=True)
def scatter_nd_add(x, index, updates, name=None):
    def fn(v, idx, upd):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply_op("scatter_nd_add", fn,
                    (x, targ(index), targ(updates)))


@register_op("scatter_nd", category="manipulation")
def scatter_nd(index, updates, shape, name=None):
    shp = _static_shape(shape)
    def fn(idx, upd):
        return jnp.zeros(shp, upd.dtype).at[
            tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply_op("scatter_nd", fn, (targ(index), targ(updates)))


@register_op("index_select", category="manipulation", tensor_method=True)
def index_select(x, index, axis=0, name=None):
    return apply_op("index_select",
                    lambda v, i: jnp.take(v, i, axis=int(axis)),
                    (x, targ(index)))


@register_op("index_sample", category="manipulation", tensor_method=True)
def index_sample(x, index, name=None):
    return apply_op("index_sample",
                    lambda v, i: jnp.take_along_axis(v, i, axis=1),
                    (x, targ(index)))


@register_op("index_add", category="manipulation", tensor_method=True,
             inplace_alias=True)
def index_add(x, index, axis, value, name=None):
    def fn(v, idx, val):
        moved = jnp.moveaxis(v, axis, 0)
        val_m = jnp.moveaxis(val, axis, 0)
        out = moved.at[idx].add(val_m)
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_add", fn, (x, targ(index), targ(value)))


@register_op("index_put", category="manipulation", tensor_method=True,
             inplace_alias=True)
def index_put(x, indices, value, accumulate=False, name=None):
    idxs = tuple(targ(i) for i in indices)
    def fn(v, val, *idx):
        if accumulate:
            return v.at[idx].add(val)
        return v.at[idx].set(val)
    return apply_op("index_put", fn, (x, targ(value), *idxs))


@register_op("take_along_axis", category="manipulation", tensor_method=True)
def take_along_axis(x, indices, axis, broadcast=True, name=None):
    return apply_op("take_along_axis",
                    lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                    (x, targ(indices)))


@register_op("put_along_axis", category="manipulation", tensor_method=True,
             inplace_alias=True)
def put_along_axis(x, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def fn(v, idx, val):
        val = jnp.broadcast_to(val, idx.shape) if broadcast else val
        if reduce == "assign":
            return jnp.put_along_axis(v, idx, val, axis=axis, inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply",
                "amin": "min", "amax": "max"}[reduce]
        moved_idx = [jnp.arange(s).reshape(
            [-1 if i == d else 1 for i in range(v.ndim)])
            for d, s in enumerate(idx.shape)]
        moved_idx[axis] = idx
        at = v.at[tuple(moved_idx)]
        return {"add": at.add, "multiply": at.multiply,
                "min": at.min, "max": at.max}[mode](val)
    return apply_op("put_along_axis", fn,
                    (x, targ(indices), targ(values)))


@register_op("take", category="manipulation", tensor_method=True)
def take(x, index, mode="raise", name=None):
    m = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply_op("take",
                    lambda v, i: jnp.take(v.reshape(-1), i.reshape(-1),
                                          mode=m).reshape(i.shape),
                    (x, targ(index)))


@register_op("masked_select", category="manipulation", tensor_method=True)
def masked_select(x, mask, name=None):
    # Dynamic output shape: the mask is concretized on host (eager only), but
    # the gather itself stays on the tape so gradients flow.
    m = np.broadcast_to(np.asarray(as_value(mask)),
                        tuple(as_value(x).shape))
    idx = tuple(jnp.asarray(i) for i in np.nonzero(m))
    return apply_op("masked_select", lambda v, *ii: v[ii], (x, *idx))


@register_op("masked_fill", category="manipulation", tensor_method=True,
             inplace_alias=True)
def masked_fill(x, mask, value, name=None):
    return apply_op("masked_fill",
                    lambda v, m, val: jnp.where(m, val, v),
                    (x, targ(mask), targ(value)))


@register_op("masked_scatter", category="manipulation", tensor_method=True,
             inplace_alias=True)
def masked_scatter(x, mask, value, name=None):
    v = np.asarray(as_value(x)).copy()
    m = np.asarray(as_value(mask))
    m = np.broadcast_to(m, v.shape)
    vals = np.asarray(as_value(value)).reshape(-1)
    v[m] = vals[: int(m.sum())]
    return wrap(jnp.asarray(v))


@register_op("where", category="manipulation", tensor_method=True)
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where", jnp.where,
                    (targ(condition), targ(x), targ(y)))


@register_op("nonzero", category="manipulation", tensor_method=True)
def nonzero(x, as_tuple=False, name=None):
    v = np.asarray(as_value(x))
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(wrap(jnp.asarray(i[:, None], jnp.int64)) for i in nz)
    return wrap(jnp.asarray(np.stack(nz, -1), jnp.int64))


@register_op("sort", category="manipulation", tensor_method=True)
def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis, stable=stable or True)
        return jnp.flip(out, axis) if descending else out
    return apply_op("sort", fn, (x,))


@register_op("argsort", category="manipulation", tensor_method=True)
def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(v):
        out = jnp.argsort(v, axis=axis, stable=True)
        return jnp.flip(out, axis) if descending else out
    return apply_op("argsort", fn, (x,))


@register_op("topk", category="manipulation", tensor_method=True)
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)
    def fn(v):
        ax = axis % v.ndim
        moved = jnp.moveaxis(v, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, kk)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply_op("topk", fn, (x,))


@register_op("searchsorted", category="manipulation")
def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    return apply_op(
        "searchsorted",
        lambda s, v: jnp.searchsorted(s, v, side=side).astype(d),
        (targ(sorted_sequence), targ(values)))


@register_op("bucketize", category="manipulation", tensor_method=True)
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


@register_op("unique", category="manipulation", tensor_method=True)
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = np.asarray(as_value(x))
    res = np.unique(v, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return wrap(jnp.asarray(res))
    outs = [wrap(jnp.asarray(r)) for r in res]
    return tuple(outs)


@register_op("unique_consecutive", category="manipulation", tensor_method=True)
def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    v = np.asarray(as_value(x))
    if axis is None:
        v = v.reshape(-1)
        change = np.concatenate([[True], v[1:] != v[:-1]])
    else:
        raise NotImplementedError("unique_consecutive with axis")
    out = v[change]
    rets = [wrap(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(change) - 1
        rets.append(wrap(jnp.asarray(inv, np.int64)))
    if return_counts:
        idx = np.nonzero(change)[0]
        counts = np.diff(np.concatenate([idx, [len(v)]]))
        rets.append(wrap(jnp.asarray(counts, np.int64)))
    return rets[0] if len(rets) == 1 else tuple(rets)


@register_op("repeat_interleave", category="manipulation", tensor_method=True)
def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        r = np.asarray(repeats.numpy())
        v = np.asarray(as_value(x))
        return wrap(jnp.asarray(np.repeat(v, r, axis=axis)))
    return apply_op("repeat_interleave",
                    lambda v: jnp.repeat(v, repeats, axis=axis), (x,))


@register_op("pad", category="manipulation")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW",
        pad_from_left_axis=True, name=None):
    pad_list = _static_shape(pad) if not isinstance(pad, (list, tuple)) \
        else [int(p.item()) if isinstance(p, Tensor) else int(p) for p in pad]

    def fn(v):
        nd = v.ndim
        if len(pad_list) == 2 * nd:
            # paddle "every dim" form: [d0_l, d0_r, d1_l, d1_r, ...]
            pairs = [(pad_list[2 * i], pad_list[2 * i + 1])
                     for i in range(nd)]
        else:
            # NCHW/NCDHW spatial form: pads applied to trailing spatial dims,
            # ordered last-dim-first like the reference.
            k = len(pad_list) // 2
            pairs = [(0, 0)] * nd
            if data_format.startswith("NC"):
                spatial = list(range(2, 2 + k))
            else:
                spatial = list(range(1, 1 + k))
            for i, d in enumerate(reversed(spatial)):
                pairs[d] = (pad_list[2 * i], pad_list[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, pairs, mode=jmode, constant_values=value)
        return jnp.pad(v, pairs, mode=jmode)
    return apply_op("pad", fn, (x,))


@register_op("slice", category="manipulation")
def slice(input, axes, starts, ends, name=None):
    def fn(v):
        sl = [np.s_[:]] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            s = int(s.item()) if isinstance(s, Tensor) else int(s)
            e = int(e.item()) if isinstance(e, Tensor) else int(e)
            sl[ax] = np.s_[s:e]
        return v[tuple(sl)]
    return apply_op("slice", fn, (input,))


@register_op("strided_slice", category="manipulation")
def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(v):
        sl = [np.s_[:]] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = np.s_[int(s):int(e):int(st)]
        return v[tuple(sl)]
    return apply_op("strided_slice", fn, (x,))


@register_op("crop", category="manipulation")
def crop(x, shape=None, offsets=None, name=None):
    shp = _static_shape(shape)
    offs = _static_shape(offsets) if offsets is not None else (0,) * len(shp)
    def fn(v):
        sl = tuple(np.s_[o:o + (s if s != -1 else v.shape[i] - o)]
                   for i, (o, s) in enumerate(zip(offs, shp)))
        return v[sl]
    return apply_op("crop", fn, (x,))


@register_op("shard_index", category="manipulation")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Parity: paddle.shard_index (used by distributed embedding)."""
    size = (index_num + nshards - 1) // nshards
    def fn(v):
        shard = v // size
        local = v % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return apply_op("shard_index", fn, (input,))


@register_op("kron", category="manipulation", tensor_method=True)
def kron(x, y, name=None):
    return apply_op("kron", jnp.kron, (x, targ(y)))


@register_op("view_as", category="manipulation", tensor_method=True)
def view_as(x, other, name=None):
    shp = tuple(as_value(other).shape)
    return apply_op("view_as", lambda v: v.reshape(shp), (x,))


@register_op("as_strided", category="manipulation", tensor_method=True)
def as_strided(x, shape, stride, offset=0, name=None):
    v = np.asarray(as_value(x))
    itemsize = v.itemsize
    out = np.lib.stride_tricks.as_strided(
        v.reshape(-1)[offset:], shape,
        [s * itemsize for s in stride])
    return wrap(jnp.asarray(out.copy()))


@register_op("tensordot", category="manipulation")
def tensordot(x, y, axes=2, name=None):
    return apply_op("tensordot",
                    lambda a, b: jnp.tensordot(a, b, axes),
                    (x, targ(y)))


@register_op("atleast_1d", category="manipulation")
def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, (t,)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


@register_op("atleast_2d", category="manipulation")
def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, (t,)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


@register_op("atleast_3d", category="manipulation")
def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, (t,)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


@register_op("block_diag", category="manipulation")
def block_diag(inputs, name=None):
    """Parity: paddle.block_diag — block-diagonal matrix from a list of
    2-D (or promotable) tensors."""
    mats = list(inputs)

    def fn(*vals):
        vs = [jnp.atleast_2d(v) for v in vals]
        R = sum(v.shape[0] for v in vs)
        C = sum(v.shape[1] for v in vs)
        out = jnp.zeros((R, C), vs[0].dtype)
        r = c = 0
        for v in vs:
            out = jax.lax.dynamic_update_slice(out, v.astype(out.dtype),
                                               (r, c))
            r += v.shape[0]
            c += v.shape[1]
        return out
    return apply_op("block_diag", fn, tuple(mats))


@register_op("pdist", category="manipulation")
def pdist(x, p=2.0, name=None):
    """Parity: paddle.pdist — condensed pairwise p-distance of the rows
    of a 2-D tensor (length n*(n-1)/2)."""
    def fn(v):
        n = v.shape[0]
        iu, ju = jnp.triu_indices(n, k=1)
        diff = v[iu] - v[ju]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
        if p == float("inf"):
            return jnp.max(jnp.abs(diff), axis=-1)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return apply_op("pdist", fn, (x,))


@register_op("cartesian_prod", category="manipulation")
def cartesian_prod(x, name=None):
    """Parity: paddle.cartesian_prod — cartesian product of 1-D tensors
    (rows are tuples, itertools.product order)."""
    ts = list(x)

    def fn(*vals):
        grids = jnp.meshgrid(*vals, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply_op("cartesian_prod", fn, tuple(ts))


@register_op("positive", category="math", tensor_method=True)
def positive(x, name=None):
    """Parity: paddle.positive (+x; errors on bool like the reference)."""
    from ._helpers import as_value
    if as_value(x).dtype == jnp.bool_:
        raise TypeError("positive is not supported for bool tensors")
    return apply_op("positive", lambda v: +v, (x,))


@register_op("hstack", category="manipulation")
def hstack(x, name=None):
    """Parity: paddle.hstack."""
    ts = list(x)

    def fn(*vals):
        return jnp.hstack(vals)
    return apply_op("hstack", fn, tuple(ts))


@register_op("vstack", category="manipulation")
def vstack(x, name=None):
    ts = list(x)

    def fn(*vals):
        return jnp.vstack(vals)
    return apply_op("vstack", fn, tuple(ts))


@register_op("dstack", category="manipulation")
def dstack(x, name=None):
    ts = list(x)

    def fn(*vals):
        return jnp.dstack(vals)
    return apply_op("dstack", fn, tuple(ts))


@register_op("column_stack", category="manipulation")
def column_stack(x, name=None):
    ts = list(x)

    def fn(*vals):
        return jnp.column_stack(vals)
    return apply_op("column_stack", fn, tuple(ts))


row_stack = vstack
register("row_stack", vstack, category="manipulation")
