"""Op library assembly.

Imports every op family, attaches Tensor methods and python operator
protocol (the analog of the generated method table + math-op patch in
paddle/fluid/pybind/eager_op_function.cc and eager_math_op_patch.cc).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from . import registry
from ._helpers import as_value, wrap

from . import math as math          # noqa: E402
from . import creation as creation  # noqa: E402
from . import manipulation as manipulation  # noqa: E402
from . import reduction as reduction        # noqa: E402
from . import linalg as linalg      # noqa: E402
from . import logic as logic        # noqa: E402
from . import random as random      # noqa: E402
from . import extras as extras      # noqa: E402

from .registry import registered_ops, get_op  # noqa: F401

# Re-export every registered op at package level.
for _name, _opdef in registry.registered_ops().items():
    globals().setdefault(_name, _opdef.fn)

# top-level inplace variants (paddle.cumsum_ etc.)
from . import inplace as _inplace_mod  # noqa: E402
for _name, _fn in _inplace_mod.build().items():
    globals().setdefault(_name, _fn)

# plain-function extras (not dispatch-registered)
from .extras import (broadcast_shape, is_complex, is_floating_point,  # noqa
                     is_integer, create_tensor, create_parameter,
                     index_fill_, gammaln_, multigammaln_)


# ---------------------------------------------------------------------------
# Tensor indexing (__getitem__ / __setitem__), incl. Tensor indices.
# Parity: paddle Tensor indexing (python/paddle/base/variable_index.py).
# ---------------------------------------------------------------------------
def _norm_index(item):
    """Split an index spec into a static template + dynamic tensor operands."""
    if not isinstance(item, tuple):
        item = (item,)
    template = []
    tensor_args = []
    for it in item:
        if isinstance(it, Tensor):
            if it.ndim == 0:
                template.append(("static", int(it.item())))
            elif np.issubdtype(np.asarray(it._value).dtype, np.bool_):
                template.append(("static", np.asarray(it._value)))
            else:
                template.append(("tensor", len(tensor_args)))
                tensor_args.append(it)
        elif isinstance(it, (list, np.ndarray)) and not isinstance(it, bool):
            arr = np.asarray(it)
            template.append(("static", arr))
        else:
            template.append(("static", it))
    return template, tensor_args


def _build_index(template, vals):
    out = []
    for kind, payload in template:
        if kind == "tensor":
            out.append(vals[payload])
        else:
            out.append(payload)
    return tuple(out)


def _getitem(self, item):
    template, tensor_args = _norm_index(item)
    has_bool = _index_has_bool(template)
    if has_bool:
        # boolean masks produce dynamic shapes: eager host-side path
        idx = _build_index(template, [np.asarray(t._value)
                                      for t in tensor_args])
        return wrap(jnp.asarray(np.asarray(self._value)[idx]))

    def fn(v, *ts):
        return v[_build_index(template, ts)]
    return apply_op("getitem", fn, (self, *tensor_args))


def _index_has_bool(template):
    for kind, payload in template:
        if kind == "static" and isinstance(payload, np.ndarray) \
                and payload.dtype == np.bool_:
            return True
    return False


def _setitem(self, item, value):
    template, tensor_args = _norm_index(item)
    if _index_has_bool(template):
        v = np.asarray(self._value).copy()
        idx = _build_index(template, [np.asarray(t._value)
                                      for t in tensor_args])
        v[idx] = np.asarray(as_value(value))
        self._value = jnp.asarray(v)
        return

    def fn(v, val, *ts):
        return v.at[_build_index(template, ts)].set(val)
    value = value if isinstance(value, Tensor) else as_value(value)
    out = apply_op("setitem", fn, (self, value, *tensor_args))
    # in-place rebind with tape continuity (paddle inplace-op semantics)
    self._inplace_assign(out)


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem


# ---------------------------------------------------------------------------
# Python operator protocol.
# ---------------------------------------------------------------------------
def _binop(opfn, swap=False):
    def method(self, other):
        if swap:
            return opfn(Tensor(other) if not isinstance(other, Tensor)
                        else other, self)
        return opfn(self, other)
    return method


Tensor.__add__ = _binop(math.add)
Tensor.__radd__ = _binop(math.add, swap=True)
Tensor.__sub__ = _binop(math.subtract)
Tensor.__rsub__ = _binop(math.subtract, swap=True)
Tensor.__mul__ = _binop(math.multiply)
Tensor.__rmul__ = _binop(math.multiply, swap=True)
Tensor.__truediv__ = _binop(math.divide)
Tensor.__rtruediv__ = _binop(math.divide, swap=True)
Tensor.__floordiv__ = _binop(math.floor_divide)
Tensor.__rfloordiv__ = _binop(math.floor_divide, swap=True)
Tensor.__mod__ = _binop(math.mod)
Tensor.__rmod__ = _binop(math.mod, swap=True)
Tensor.__pow__ = _binop(math.pow)
Tensor.__rpow__ = _binop(math.pow, swap=True)
Tensor.__matmul__ = _binop(linalg.matmul)
Tensor.__rmatmul__ = _binop(linalg.matmul, swap=True)
Tensor.__neg__ = lambda self: math.neg(self)
Tensor.__abs__ = lambda self: math.abs(self)
Tensor.__invert__ = lambda self: math.logical_not(self) \
    if self.dtype == jnp.bool_ else math.bitwise_not(self)
Tensor.__eq__ = lambda self, other: math.equal(self, other)
Tensor.__ne__ = lambda self, other: math.not_equal(self, other)
Tensor.__lt__ = _binop(math.less_than)
Tensor.__le__ = _binop(math.less_equal)
Tensor.__gt__ = _binop(math.greater_than)
Tensor.__ge__ = _binop(math.greater_equal)
Tensor.__and__ = _binop(math.bitwise_and)
Tensor.__or__ = _binop(math.bitwise_or)
Tensor.__xor__ = _binop(math.bitwise_xor)
Tensor.__lshift__ = _binop(math.bitwise_left_shift)
Tensor.__rshift__ = _binop(math.bitwise_right_shift)


def _inplace_binop(opfn):
    def method(self, other):
        return self._inplace_assign(opfn(self, other))
    return method


Tensor.__iadd__ = _inplace_binop(math.add)
Tensor.__isub__ = _inplace_binop(math.subtract)
Tensor.__imul__ = _inplace_binop(math.multiply)
Tensor.__itruediv__ = _inplace_binop(math.divide)

# property-style helpers
Tensor.T = property(lambda self: manipulation.transpose(self))
Tensor.mT = property(lambda self: manipulation.swapaxes(self, -1, -2))

registry.attach_tensor_methods(Tensor)
