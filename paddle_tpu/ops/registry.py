"""Op registry.

Capability parity with the reference's YAML op registry
(reference: paddle/phi/api/yaml/ops.yaml + backward.yaml — the single source
of truth from which the C++ API, GradNodes and Python bindings are generated;
registration macro paddle/phi/core/kernel_registry.h:196).

TPU-native design: an op is a named pure JAX function.  Forward lowering to
XLA replaces per-backend kernels; the backward "kernel" is the VJP captured at
dispatch time (see core/dispatch.py), so registering the forward implies the
backward — the analog of the ops.yaml/backward.yaml pairing without a second
registry.  The registry powers: Tensor method attachment, the OpTest harness,
AMP op lists, and introspection (``paddle_tpu.ops.registered_ops()``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Sequence

_OPS: Dict[str, "OpDef"] = {}


@dataclass
class OpDef:
    name: str
    fn: Callable                      # python-facing function (Tensor level)
    category: str = "misc"
    tensor_method: bool = False       # attach as Tensor.<name>
    method_name: Optional[str] = None
    inplace_alias: bool = False       # also expose <name>_ in-place variant
    doc: str = ""


def register_op(name: str, category: str = "misc", tensor_method: bool = False,
                method_name: Optional[str] = None, inplace_alias: bool = False):
    """Decorator registering a python-level op."""

    def deco(fn):
        _OPS[name] = OpDef(name, fn, category, tensor_method,
                           method_name or name, inplace_alias, fn.__doc__ or "")
        return fn

    return deco


def register(name: str, fn: Callable, **kw):
    _OPS[name] = OpDef(name, fn, kw.get("category", "misc"),
                       kw.get("tensor_method", False),
                       kw.get("method_name", name),
                       kw.get("inplace_alias", False), fn.__doc__ or "")
    return fn


def get_op(name: str) -> OpDef:
    return _OPS[name]


def registered_ops() -> Dict[str, OpDef]:
    return dict(_OPS)


def attach_tensor_methods(tensor_cls):
    """Attach registered ops as Tensor methods (the analog of the generated
    method table in paddle/fluid/pybind/eager_op_function.cc)."""
    for opdef in _OPS.values():
        if not opdef.tensor_method:
            continue
        name = opdef.method_name
        if name in tensor_cls.__dict__:
            continue
        setattr(tensor_cls, name, opdef.fn)
        if opdef.inplace_alias and name + "_" not in tensor_cls.__dict__:
            def make_inplace(f):
                def inplace(self, *a, **k):
                    return self._inplace_assign(f(self, *a, **k))
                return inplace
            setattr(tensor_cls, name + "_", make_inplace(opdef.fn))
