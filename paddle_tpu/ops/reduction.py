"""Reduction / scan / statistics ops.

Parity: python/paddle/tensor/math.py + stat.py (reference), phi reduce
kernels.  XLA lowers these to tiled tree-reductions on the VPU.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.scipy import special as jspecial

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap, targ


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _def_reduce(name, jfn, int_promote=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _norm_axis(axis)
        d = _dt.convert_dtype(dtype) if dtype else None

        def fn(v):
            out = jfn(v, axis=ax, keepdims=keepdim)
            if d is not None:
                out = out.astype(d)
            elif int_promote and jnp.issubdtype(v.dtype, jnp.integer):
                out = out.astype(jnp.int64)
            return out
        return apply_op(op.__op_name__, fn, (x,))

    op.__op_name__ = name
    op.__name__ = name
    register(name, op, category="reduction", tensor_method=True)
    return op


sum = _def_reduce("sum", jnp.sum, int_promote=True)
mean = _def_reduce("mean", jnp.mean)
prod = _def_reduce("prod", jnp.prod, int_promote=True)
nansum = _def_reduce("nansum", jnp.nansum, int_promote=True)
nanmean = _def_reduce("nanmean", jnp.nanmean)
amax = _def_reduce("amax", jnp.amax)
amin = _def_reduce("amin", jnp.amin)


def _def_minmax(name, jfn):
    def op(x, axis=None, keepdim=False, name=None):
        ax = _norm_axis(axis)
        return apply_op(op.__op_name__,
                        lambda v: jfn(v, axis=ax, keepdims=keepdim), (x,))
    op.__op_name__ = name
    op.__name__ = name
    register(name, op, category="reduction", tensor_method=True)
    return op


max = _def_minmax("max", jnp.max)
min = _def_minmax("min", jnp.min)


@register_op("all", category="reduction", tensor_method=True)
def all(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("all", lambda v: jnp.all(v, axis=ax, keepdims=keepdim),
                    (x,))


@register_op("any", category="reduction", tensor_method=True)
def any(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("any", lambda v: jnp.any(v, axis=ax, keepdims=keepdim),
                    (x,))


@register_op("argmax", category="reduction", tensor_method=True)
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(
        "argmax",
        lambda v: jnp.argmax(v, axis=axis, keepdims=keepdim).astype(d), (x,))


@register_op("argmin", category="reduction", tensor_method=True)
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(
        "argmin",
        lambda v: jnp.argmin(v, axis=axis, keepdims=keepdim).astype(d), (x,))


@register_op("cumsum", category="reduction", tensor_method=True)
def cumsum(x, axis=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype else None
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            out = jnp.cumsum(v)
        else:
            out = jnp.cumsum(v, axis=axis)
        return out.astype(d) if d else out
    return apply_op("cumsum", fn, (x,))


@register_op("cumprod", category="reduction", tensor_method=True)
def cumprod(x, dim=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype) if dtype else None
    def fn(v):
        out = jnp.cumprod(v.reshape(-1) if dim is None else v,
                          axis=None if dim is None else dim)
        return out.astype(d) if d else out
    return apply_op("cumprod", fn, (x,))


@register_op("cummax", category="reduction", tensor_method=True)
def cummax(x, axis=None, dtype="int64", name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=ax)
        idx = jnp.where(vv == vals, jnp.arange(vv.shape[ax]).reshape(
            [-1 if i == ax % vv.ndim else 1 for i in range(vv.ndim)]), 0)
        idx = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
        return vals, idx.astype(_dt.convert_dtype(dtype))
    return apply_op("cummax", fn, (x,))


@register_op("cummin", category="reduction", tensor_method=True)
def cummin(x, axis=None, dtype="int64", name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        vals = jax.lax.associative_scan(jnp.minimum, vv, axis=ax)
        idx = jnp.where(vv == vals, jnp.arange(vv.shape[ax]).reshape(
            [-1 if i == ax % vv.ndim else 1 for i in range(vv.ndim)]), 0)
        idx = jax.lax.associative_scan(jnp.maximum, idx, axis=ax)
        return vals, idx.astype(_dt.convert_dtype(dtype))
    return apply_op("cummin", fn, (x,))


@register_op("logsumexp", category="reduction", tensor_method=True)
def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        "logsumexp",
        lambda v: jspecial.logsumexp(v, axis=ax, keepdims=keepdim), (x,))


@register_op("logcumsumexp", category="reduction", tensor_method=True)
def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        return jax.lax.cumlogsumexp(vv, axis=ax)
    return apply_op("logcumsumexp", fn, (x,))


@register_op("std", category="reduction", tensor_method=True)
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op(
        "std", lambda v: jnp.std(v, axis=ax, ddof=ddof, keepdims=keepdim),
        (x,))


@register_op("var", category="reduction", tensor_method=True)
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op(
        "var", lambda v: jnp.var(v, axis=ax, ddof=ddof, keepdims=keepdim),
        (x,))


@register_op("median", category="reduction", tensor_method=True)
def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(v):
        if mode == "avg":
            return jnp.median(v, axis=axis, keepdims=keepdim)
        # min mode: lower median + its index
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        n = vv.shape[ax]
        k = (n - 1) // 2
        srt = jnp.sort(vv, axis=ax)
        arg = jnp.argsort(vv, axis=ax)
        vals = jnp.take(srt, k, axis=ax)
        idxs = jnp.take(arg, k, axis=ax)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idxs = jnp.expand_dims(idxs, ax)
        return vals, idxs.astype(jnp.int64)
    return apply_op("median", fn, (x,))


@register_op("nanmedian", category="reduction", tensor_method=True)
def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmedian",
                    lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim),
                    (x,))


@register_op("quantile", category="reduction", tensor_method=True)
def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    return apply_op(
        "quantile",
        lambda v: jnp.quantile(v, jnp.asarray(q), axis=axis,
                               keepdims=keepdim, method=interpolation), (x,))


@register_op("nanquantile", category="reduction", tensor_method=True)
def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(
        "nanquantile",
        lambda v: jnp.nanquantile(v, jnp.asarray(q), axis=axis,
                                  keepdims=keepdim), (x,))


@register_op("kthvalue", category="reduction", tensor_method=True)
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        ax = axis % v.ndim
        srt = jnp.sort(v, axis=ax)
        arg = jnp.argsort(v, axis=ax)
        vals = jnp.take(srt, k - 1, axis=ax)
        idxs = jnp.take(arg, k - 1, axis=ax)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idxs = jnp.expand_dims(idxs, ax)
        return vals, idxs.astype(jnp.int64)
    return apply_op("kthvalue", fn, (x,))


@register_op("mode", category="reduction", tensor_method=True)
def mode(x, axis=-1, keepdim=False, name=None):
    v = np.asarray(as_value(x))
    ax = axis % v.ndim
    moved = np.moveaxis(v, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], v.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shape = moved.shape[:-1]
    vals = vals.reshape(shape)
    idxs = idxs.reshape(shape)
    if keepdim:
        vals = np.expand_dims(vals, ax)
        idxs = np.expand_dims(idxs, ax)
    return wrap(jnp.asarray(vals)), wrap(jnp.asarray(idxs))


@register_op("count_nonzero", category="reduction", tensor_method=True)
def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op(
        "count_nonzero",
        lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim).astype(
            jnp.int64), (x,))


@register_op("histogram", category="reduction", tensor_method=True)
def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    v = np.asarray(as_value(input))
    lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
    w = np.asarray(as_value(weight)) if weight is not None else None
    hist, _ = np.histogram(v, bins=bins, range=(lo, hi), weights=w,
                           density=density)
    return wrap(jnp.asarray(hist if density else hist.astype(np.int64)))


@register_op("histogramdd", category="reduction")
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    v = np.asarray(as_value(x))
    w = np.asarray(as_value(weights)) if weights is not None else None
    hist, edges = np.histogramdd(v, bins=bins, range=ranges, density=density,
                                 weights=w)
    return wrap(jnp.asarray(hist)), [wrap(jnp.asarray(e)) for e in edges]


@register_op("bincount", category="reduction", tensor_method=True)
def bincount(x, weights=None, minlength=0, name=None):
    v = np.asarray(as_value(x))
    w = np.asarray(as_value(weights)) if weights is not None else None
    return wrap(jnp.asarray(np.bincount(v, w, minlength)))
