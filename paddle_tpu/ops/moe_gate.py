"""Shared MoE gating + expert dispatch primitives (raw-jnp level).

The ONE top-k gate / dispatch implementation in the repo.  Callers:

- ``models/mixtral.py`` eager block — GShard capacity buffers with
  drops, plus the load-balancing aux term (computed by the caller so
  the side state never enters a serving trace);
- ``incubate/distributed/models/moe/gate.py`` — NaiveGate/GShardGate/
  SwitchGate all route through :func:`topk_gate` (no second
  softmax/top-k copy drifting out of sync);
- ``jit/serving_step.py`` — :func:`moe_ffn` is the fused dropless MoE
  FFN inside the compiled serving steps, optionally expert-parallel
  over an ``ep`` mesh axis with ``jax.lax.all_to_all`` dispatch/combine
  (the reference's global_scatter/global_gather pair, emitted inside
  the ONE compiled launch).

Everything here is pure jnp -> safe both under ``apply_op`` eager
dispatch and inside jit/shard_map traced bodies.  No host transfers, no
shape branches on traced values, no PRNG.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "topk_gate", "assignment_slots", "dispatch_to_buffers",
    "grouped_expert_swiglu", "combine_from_buffers", "moe_ffn",
]


def topk_gate(logits, k, renormalize=True):
    """Softmax + top-k routing from raw router logits ``[N, E]``.

    Returns ``(top_w f32 [N,k], top_i int32 [N,k], probs f32 [N,E])``.
    ``renormalize=True`` rescales the selected weights to sum to 1
    (Mixtral convention); Switch-style gates pass ``False``.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_w, top_i = jax.lax.top_k(probs, k)
    if renormalize:
        top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
    return top_w, top_i.astype(jnp.int32), probs


def assignment_slots(top_i, num_experts):
    """Per-assignment capacity slot: running count per expert over the
    flattened ``[N*k]`` assignment order (GShard dense-dispatch
    position, one-hot cumsum — never an ``[N,k,E,C]`` one-hot).

    Returns ``(slot int32 [N,k], oh f32 [N,k,E])``; ``oh`` is handed
    back so aux-loss callers don't recompute the one-hot.
    """
    oh = jax.nn.one_hot(top_i, num_experts, dtype=jnp.float32)
    pos = jnp.cumsum(oh.reshape(-1, num_experts), axis=0).reshape(
        oh.shape) - 1.0
    slot = jnp.sum(pos * oh, axis=-1).astype(jnp.int32)
    return slot, oh


def dispatch_to_buffers(x, top_i, slot, keep, num_experts, capacity):
    """Scatter tokens into ``[E, C, D]`` expert buffers (f32 scatter-add,
    cast back to ``x.dtype``).  ``keep=None`` means dropless (every
    assignment has a slot); otherwise over-capacity rows scatter zeros.
    """
    n, k = top_i.shape
    vf = x.astype(jnp.float32)
    if keep is None:
        src = jnp.broadcast_to(vf[:, None, :], (n, k, vf.shape[1]))
    else:
        src = vf[:, None, :] * keep[..., None]
    src = src.reshape(n * k, -1)
    slot_c = jnp.clip(slot, 0, capacity - 1)
    zeros = jnp.zeros((num_experts, capacity, vf.shape[1]), jnp.float32)
    return zeros.at[top_i.reshape(-1),
                    slot_c.reshape(-1)].add(src).astype(x.dtype)


def grouped_expert_swiglu(disp, wg, wu, wd):
    """Batched expert SwiGLU: the whole bank in three MXU einsums.

    ``disp [E, C, D]``, ``wg/wu [E, D, M]``, ``wd [E, M, D]`` ->
    ``[E, C, D]``.  Row results are independent of buffer contents, so
    capacity-buffer padding never perturbs real tokens.
    """
    g = jnp.einsum("ecd,edm->ecm", disp, wg)
    u = jnp.einsum("ecd,edm->ecm", disp, wu)
    h = jax.nn.silu(g.astype(jnp.float32)).astype(disp.dtype) * u
    return jnp.einsum("ecm,emd->ecd", h, wd)


def combine_from_buffers(eo, top_i, slot, top_w, keep=None):
    """Gather each assignment's expert output and k-sum with routing
    weights.  Returns f32 ``[N, D]`` (caller casts).  ``keep`` masks
    dropped assignments (eager capacity path)."""
    n, k = top_i.shape
    capacity = eo.shape[1]
    slot_c = jnp.clip(slot, 0, capacity - 1)
    picked = eo[top_i.reshape(-1), slot_c.reshape(-1)].reshape(n, k, -1)
    w_eff = top_w.astype(jnp.float32)
    if keep is not None:
        w_eff = (top_w * keep).astype(jnp.float32)
    return jnp.sum(picked.astype(jnp.float32) * w_eff[..., None], axis=1)


def moe_ffn(x, gate_w, wg, wu, wd, *, top_k, ep_axis=None, ep_degree=1):
    """Dropless fused MoE FFN over a flat token block ``x [N, D]``.

    ``gate_w [D, E_total]`` replicated; ``wg/wu/wd`` the LOCAL expert
    shard ``[El, ., .]`` (``El = E_total/ep``; the full bank when
    ``ep_degree == 1``).

    Local path (``ep_degree <= 1``): dropless capacity ``N*top_k``
    bounds the worst-case per-expert load, so no assignment is ever
    dropped — the buffers are the GShard layout of the eager block with
    the drop mask provably all-True.

    ep path (inside shard_map over ``ep_axis``): chip ``r`` gates its
    token stripe ``x[r*Tl:(r+1)*Tl]``, scatters into a per-expert send
    buffer ``[E_total, Tl*k, D]``, ``all_to_all`` ships each expert
    owner its slices, grouped SwiGLU runs on the local ``[El, ., .]``
    shard, ``all_to_all`` ships outputs back, the weighted combine runs
    on the token's home chip, and ``all_gather`` rebuilds the
    replicated ``[N, D]`` activation.  Requires ``ep | N`` and
    ``ep | E_total`` (validated at engine construction).
    """
    n, d = x.shape
    e_local = wg.shape[0]
    if ep_axis is None or ep_degree <= 1:
        logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        top_w, top_i, _ = topk_gate(logits, top_k)
        slot, _ = assignment_slots(top_i, e_local)
        disp = dispatch_to_buffers(x, top_i, slot, None, e_local,
                                   n * top_k)
        eo = grouped_expert_swiglu(disp, wg, wu, wd)
        return combine_from_buffers(eo, top_i, slot, top_w).astype(x.dtype)

    e_total = e_local * ep_degree
    tl = n // ep_degree                 # token stripe per chip
    cl = tl * top_k                     # dropless send capacity
    r = jax.lax.axis_index(ep_axis)
    x_r = jax.lax.dynamic_slice_in_dim(x, r * tl, tl, axis=0)
    logits = x_r.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    top_w, top_i, _ = topk_gate(logits, top_k)
    slot, _ = assignment_slots(top_i, e_total)
    disp = dispatch_to_buffers(x_r, top_i, slot, None, e_total, cl)
    # dispatch: chip g receives [ep, El, Cl, D]; recv[r] = chip r's
    # assignments destined to chip g's experts
    recv = jax.lax.all_to_all(disp, ep_axis, split_axis=0,
                              concat_axis=0, tiled=True)
    work = jnp.swapaxes(recv.reshape(ep_degree, e_local, cl, d),
                        0, 1).reshape(e_local, ep_degree * cl, d)
    eo = grouped_expert_swiglu(work, wg, wu, wd)
    back = jnp.swapaxes(eo.reshape(e_local, ep_degree, cl, d),
                        0, 1).reshape(e_total, cl, d)
    # combine: ship outputs back to each assignment's home chip; after
    # the exchange chip r holds [E_total, Cl, D] aligned with its own
    # (top_i, slot) tables
    back = jax.lax.all_to_all(back, ep_axis, split_axis=0,
                              concat_axis=0, tiled=True)
    out_r = combine_from_buffers(back, top_i, slot,
                                 top_w).astype(x.dtype)
    return jax.lax.all_gather(out_r, ep_axis, axis=0, tiled=True)
