"""Linear algebra ops.

Parity: python/paddle/tensor/linalg.py (reference), phi matmul/blas kernels
(paddle/phi/kernels/funcs/blas/).  matmul is THE MXU op — kept big, batched
and bf16-friendly; decompositions fall back to XLA's LAPACK-style custom
calls (CPU) / approximations where XLA lacks a TPU lowering.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..core import dtypes as _dt
from .registry import register_op, register
from ._helpers import as_value, wrap, targ


@register_op("matmul", category="linalg", tensor_method=True)
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Parity: paddle.matmul (reference call stack SURVEY §3.1;
    phi::MatmulKernel). Lowered to a single XLA dot_general on the MXU."""
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op("matmul", fn, (x, targ(y)))


register("mm", matmul, category="linalg", tensor_method=True,
         method_name="mm")


@register_op("dot", category="linalg", tensor_method=True)
def dot(x, y, name=None):
    def fn(a, b):
        return jnp.sum(a * b, axis=-1)
    return apply_op("dot", fn, (x, targ(y)))


@register_op("bmm", category="linalg", tensor_method=True)
def bmm(x, y, name=None):
    return apply_op("bmm", jnp.matmul, (x, targ(y)))


@register_op("mv", category="linalg", tensor_method=True)
def mv(x, vec, name=None):
    return apply_op("mv", jnp.matmul, (x, targ(vec)))


@register_op("t", category="linalg", tensor_method=True)
def t(input, name=None):
    def fn(v):
        return v if v.ndim < 2 else jnp.swapaxes(v, 0, 1)
    return apply_op("t", fn, (input,))


@register_op("addmm", category="linalg", tensor_method=True)
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm",
                    lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                    (input, targ(x), targ(y)))


@register_op("outer", category="linalg", tensor_method=True)
def outer(x, y, name=None):
    return apply_op("outer",
                    lambda a, b: jnp.outer(a, b), (x, targ(y)))


@register_op("inner", category="linalg", tensor_method=True)
def inner(x, y, name=None):
    return apply_op("inner", jnp.inner, (x, targ(y)))


@register_op("cross", category="linalg", tensor_method=True)
def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", fn, (x, targ(y)))


@register_op("trace", category="linalg", tensor_method=True)
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace",
                    lambda v: jnp.trace(v, offset, axis1, axis2), (x,))


@register_op("norm", category="linalg", tensor_method=True)
def norm(x, p=None, axis=None, keepdim=False, name=None):
    def fn(v):
        pp = p
        if pp is None:
            pp = "fro" if axis is None or isinstance(axis, (list, tuple)) \
                else 2
        if axis is None:
            flat = v.reshape(-1)
            if pp == "fro" or pp == 2:
                return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(flat)))).reshape(
                    () if not keepdim else (1,) * v.ndim)
            if pp == np.inf or pp == float("inf"):
                return jnp.max(jnp.abs(flat))
            if pp == -np.inf or pp == float("-inf"):
                return jnp.min(jnp.abs(flat))
            return jnp.power(jnp.sum(jnp.power(jnp.abs(flat), pp)), 1.0 / pp)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if pp == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(v)), axis=ax,
                                    keepdims=keepdim))
        if pp in (np.inf, float("inf")):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp in (-np.inf, float("-inf")):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax,
                           keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), pp), axis=ax,
                                 keepdims=keepdim), 1.0 / pp)
    return apply_op("norm", fn, (x,))


@register_op("dist", category="linalg", tensor_method=True)
def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = jnp.abs(a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p in (np.inf, float("inf")):
            return jnp.max(d)
        if p in (-np.inf, float("-inf")):
            return jnp.min(d)
        return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)
    return apply_op("dist", fn, (x, targ(y)))


@register_op("einsum", category="linalg")
def einsum(equation, *operands, name=None):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op("einsum",
                    lambda *vs: jnp.einsum(equation, *vs), operands)


@register_op("multi_dot", category="linalg")
def multi_dot(x, name=None):
    return apply_op("multi_dot",
                    lambda *vs: jnp.linalg.multi_dot(list(vs)), tuple(x))


@register_op("cholesky", category="linalg", tensor_method=True)
def cholesky(x, upper=False, name=None):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op("cholesky", fn, (x,))


@register_op("cholesky_solve", category="linalg", tensor_method=True)
def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op("cholesky_solve", fn, (x, targ(y)))


@register_op("inverse", category="linalg", tensor_method=True)
def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, (x,))


@register_op("det", category="linalg", tensor_method=True)
def det(x, name=None):
    return apply_op("det", jnp.linalg.det, (x,))


@register_op("slogdet", category="linalg", tensor_method=True)
def slogdet(x, name=None):
    def fn(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])
    return apply_op("slogdet", fn, (x,))


@register_op("svd", category="linalg", tensor_method=True)
def svd(x, full_matrices=False, name=None):
    return apply_op(
        "svd",
        lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)),
        (x,))


@register_op("qr", category="linalg", tensor_method=True)
def qr(x, mode="reduced", name=None):
    return apply_op("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), (x,))


@register_op("eig", category="linalg", tensor_method=True)
def eig(x, name=None):
    v = np.asarray(as_value(x))
    w, vecs = np.linalg.eig(v)
    return wrap(jnp.asarray(w)), wrap(jnp.asarray(vecs))


@register_op("eigh", category="linalg", tensor_method=True)
def eigh(x, UPLO="L", name=None):
    return apply_op("eigh",
                    lambda v: tuple(jnp.linalg.eigh(v,
                                                    symmetrize_input=True)),
                    (x,))


@register_op("eigvals", category="linalg", tensor_method=True)
def eigvals(x, name=None):
    v = np.asarray(as_value(x))
    return wrap(jnp.asarray(np.linalg.eigvals(v)))


@register_op("eigvalsh", category="linalg", tensor_method=True)
def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", jnp.linalg.eigvalsh, (x,))


@register_op("matrix_power", category="linalg", tensor_method=True)
def matrix_power(x, n, name=None):
    return apply_op("matrix_power",
                    lambda v: jnp.linalg.matrix_power(v, n), (x,))


@register_op("matrix_rank", category="linalg", tensor_method=True)
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(
        "matrix_rank",
        lambda v: jnp.linalg.matrix_rank(v, rtol=tol).astype(jnp.int64),
        (x,))


@register_op("pinv", category="linalg", tensor_method=True)
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv",
                    lambda v: jnp.linalg.pinv(v, rtol=rcond,
                                              hermitian=hermitian), (x,))


@register_op("solve", category="linalg", tensor_method=True)
def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, (x, targ(y)))


@register_op("triangular_solve", category="linalg", tensor_method=True)
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op("triangular_solve", fn, (x, targ(y)))


@register_op("lstsq", category="linalg", tensor_method=True)
def lstsq(x, y, rcond=None, driver=None, name=None):
    a = np.asarray(as_value(x))
    b = np.asarray(as_value(y))
    sol, res, rank, sv = np.linalg.lstsq(a, b, rcond=rcond)
    return (wrap(jnp.asarray(sol)), wrap(jnp.asarray(res)),
            wrap(jnp.asarray(rank)), wrap(jnp.asarray(sv)))


@register_op("lu", category="linalg", tensor_method=True)
def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, piv.astype(jnp.int32) + 1  # paddle uses 1-based pivots
    outs = apply_op("lu", fn, (x,))
    if get_infos:
        info = wrap(jnp.zeros((), jnp.int32))
        return outs[0], outs[1], info
    return outs


@register_op("cond", category="linalg", tensor_method=True)
def cond(x, p=None, name=None):
    return apply_op("cond_number",
                    lambda v: jnp.linalg.cond(v, p=p), (x,))


@register_op("cov", category="linalg", tensor_method=True)
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = as_value(fweights) if fweights is not None else None
    aw = as_value(aweights) if aweights is not None else None
    return apply_op(
        "cov",
        lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                          fweights=fw, aweights=aw), (x,))


@register_op("corrcoef", category="linalg", tensor_method=True)
def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar),
                    (x,))


@register_op("matrix_exp", category="linalg", tensor_method=True)
def matrix_exp(x, name=None):
    return apply_op("matrix_exp", jax.scipy.linalg.expm, (x,))


@register_op("householder_product", category="linalg")
def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        def body(i, q):
            v = jnp.where(jnp.arange(m) < i, 0.0, a[..., :, i])
            v = v.at[i].set(1.0)
            h = eye - t[..., i] * jnp.outer(v, v)
            return q @ h
        q = eye
        for i in range(n):
            q = body(i, q)
        return q[..., :, :n]
    return apply_op("householder_product", fn, (x, targ(tau)))


@register_op("pca_lowrank", category="linalg")
def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    v = np.asarray(as_value(x)).astype(np.float64)
    qq = q if q is not None else min(6, *v.shape[-2:])
    if center:
        v = v - v.mean(axis=-2, keepdims=True)
    u, s, vt = np.linalg.svd(v, full_matrices=False)
    return (wrap(jnp.asarray(u[..., :qq].astype(np.float32))),
            wrap(jnp.asarray(s[..., :qq].astype(np.float32))),
            wrap(jnp.asarray(np.swapaxes(vt, -1, -2)[..., :qq].astype(
                np.float32))))


# paddle.linalg.inv is the reference's alias of inverse
# (python/paddle/linalg.py: `from .tensor import inverse as inv`)
inv = inverse


@register_op("lu_unpack", category="linalg", tensor_method=True)
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack packed LU + 1-based pivots into (P, L, U).

    Parity: python/paddle/tensor/linalg.py:2482 (lu_unpack; phi
    lu_unpack kernel): A = P @ L @ U for (lu, piv) = paddle.linalg.lu(A).
    Pivot application is a lax.scan of row swaps so it stays traceable."""
    def fn(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        tril = jnp.tril(lu_, -1)[..., :, :k]
        eye = jnp.eye(m, k, dtype=lu_.dtype)
        L = tril + eye
        U = jnp.triu(lu_)[..., :k, :]
        piv0 = piv.astype(jnp.int32) - 1               # [..., K]

        def perm_one(p1d):
            def body(perm, ip):
                i, p = ip
                pi, pp = perm[i], perm[p]
                return perm.at[i].set(pp).at[p].set(pi), None
            perm, _ = jax.lax.scan(
                body, jnp.arange(m),
                (jnp.arange(p1d.shape[0]), p1d))
            return perm

        batch = piv0.shape[:-1]
        perms = jnp.reshape(
            jax.vmap(perm_one)(piv0.reshape((-1, piv0.shape[-1]))),
            batch + (m,))
        # rows of LU are A[perm]; A = P @ (L@U) with P[perm[i], i] = 1
        P = jnp.swapaxes(
            jax.nn.one_hot(perms, m, dtype=lu_.dtype), -1, -2)
        return P, L, U

    P, L, U = apply_op("lu_unpack", fn, (x, targ(y)))
    # flags drop outputs at the API level only (reference returns None for
    # skipped parts); everything is computed in one traced op either way
    return (P if unpack_pivots else None,
            L if unpack_ludata else None,
            U if unpack_ludata else None)
