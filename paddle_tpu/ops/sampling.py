"""On-device stochastic sampling + speculative-decode verification.

The sampling epilogue of the fused serving steps (``jit/serving_step``):
per-request temperature / top-k / top-p with a seeded COUNTER-BASED
PRNG, all traceable, so every knob and seed is plain traced DATA riding
the steps' packed int32 operand buffer — changing a temperature or a
seed never retraces a module, and ``temperature <= 0`` reduces to the
exact greedy argmax the pre-sampling engines shipped (the fast path the
default engines stay byte-identical through).

Determinism contract: the key for every random draw is
``fold_in(fold_in(PRNGKey(seed), position), stream_tag)`` where
``position`` is the GLOBAL sequence index of the token being sampled
and ``seed`` is the request's.  The counter depends on nothing but the
request's own progress, so a sampled request produces the same tokens
whether it decodes alone, batched with churn, through the split or the
mixed engine, or under tensor parallelism (the logits all-gather is
exact, the threefry math replicated) — the serving analog of the greedy
byte-parity contract.

Speculative decoding (``spec_verify``): standard accept/reject with
rejection-resampling (Leviathan et al.) — draft token ``d_j`` with
draft probability ``q_j(d_j)`` is accepted iff
``u_j < p_j(d_j) / q_j(d_j)`` against the target's filtered
distribution ``p_j``; the first rejection resamples from
``normalize(max(p_j - q_j, 0))`` and a fully-accepted chain samples the
bonus token from ``p_k``.  The output distribution is exactly ``p`` per
position.  Greedy rows (``temperature <= 0``) use the argmax-match rule
instead, which makes greedy speculative output BYTE-IDENTICAL to
non-speculative greedy — the CPU-checkable parity gate.

All math is fp32 regardless of the model dtype (like every other
logits-side reduction in the serving steps).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_logits", "filtered_probs", "spec_verify",
           "DRAFT_SEED_XOR"]

# RNG stream tags: one counter (= token position) feeds three
# independent streams so the draft's proposal draw, the verifier's
# accept draw and the rejection-resample draw never correlate.
_TAG_PROPOSE = 0
_TAG_ACCEPT = 1
_TAG_RESIDUAL = 2

# the engine XORs draft-span seeds with this (host-side, int32-safe) so
# a self-speculative draft (same weights) still proposes from an RNG
# stream independent of the target's
DRAFT_SEED_XOR = 0x5EED


def _row_key(seed, counter, tag: int):
    key = jax.random.PRNGKey(seed)
    key = jax.random.fold_in(key, counter)
    return jax.random.fold_in(key, jnp.int32(tag))


def _filter_row(l, t, k, p):
    """One [V] fp32 logits row -> tempered, top-k / top-p masked row
    (-inf outside the kept set).  ``k <= 0`` disables top-k; ``p`` out
    of (0, 1) disables top-p.  The best token is always kept, so a row
    is never fully masked.  ONE sort serves both filters: the top-k
    mask removes a SUFFIX of the descending order, so the masked array
    is still sorted and the nucleus cumsum reads it directly."""
    V = l.shape[0]
    lt = l / jnp.maximum(t, jnp.float32(1e-6))
    desc = jnp.sort(lt)[::-1]
    kk = jnp.clip(k, 1, V)
    use_k = (k > 0) & (k < V)
    k_thr = jnp.where(use_k, desc[kk - 1], -jnp.inf)
    rank = jnp.arange(V, dtype=jnp.int32)
    desc_m = jnp.where(use_k & (rank >= kk), -jnp.inf, desc)
    # nucleus over the tempered+top-k-masked distribution: keep the
    # smallest prefix (in descending-prob order) whose mass reaches p
    probs = jax.nn.softmax(desc_m)
    keep = (jnp.cumsum(probs) - probs) < p
    use_p = (p > jnp.float32(0.0)) & (p < jnp.float32(1.0))
    p_thr = jnp.where(use_p,
                      jnp.min(jnp.where(keep, desc_m, jnp.inf)),
                      -jnp.inf)
    return jnp.where(lt < jnp.maximum(k_thr, p_thr), -jnp.inf, lt)


def sample_logits(logits, temps, top_ks, top_ps, seeds, counters):
    """Sample one token per row (traceable; the steps' epilogue).

    logits [S, V]; temps/top_ps [S] fp32; top_ks/seeds/counters [S]
    int32 (``counters`` = the global position of the token being
    sampled).  Returns the [S] int32 tokens.  Rows with
    ``temperature <= 0`` take the exact greedy argmax.  The top-k /
    top-p sort pass is skipped at RUN time (one ``lax.cond`` around
    the whole batch) when no row filters — temperature-only sampling
    pays just the gumbel draw on top of the argmax."""
    lf = logits.astype(jnp.float32)
    V = lf.shape[-1]
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
    any_filter = jnp.any(((top_ks > 0) & (top_ks < V))
                         | ((top_ps > 0.0) & (top_ps < 1.0)))
    # both branches temper with the SAME division expression — a
    # reciprocal-multiply shortcut here would differ by 1 ulp from the
    # filtered branch and break the byte-identical replay contract
    # when a co-batched request toggles top-k/top-p
    lt = jax.lax.cond(
        any_filter,
        lambda x: jax.vmap(_filter_row)(x, temps, top_ks, top_ps),
        lambda x: x / jnp.maximum(temps, jnp.float32(1e-6))[:, None],
        lf)
    g = jax.vmap(lambda seed, ctr: jax.random.gumbel(
        _row_key(seed, ctr, _TAG_PROPOSE), (V,), jnp.float32)
    )(seeds, counters)
    samp = jnp.argmax(lt + g, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0, samp, greedy)


def filtered_probs(logits, temps, top_ks, top_ps):
    """[S, V] logits -> [S, V] fp32 probabilities of each row's
    filtered (tempered / top-k / top-p) distribution — the draft
    model's full proposal distribution ``q``, kept device-resident for
    the verifier's rejection-resampling."""
    lf = logits.astype(jnp.float32)
    return jax.vmap(
        lambda l, t, k, p: jax.nn.softmax(_filter_row(l, t, k, p))
    )(lf, temps, top_ks, top_ps)


def spec_verify(logits_rows, draft_tokens, n_draft, temps, top_ks,
                top_ps, seeds, base_pos, q_rows=None):
    """Vectorized speculative accept/reject + resample (traceable; the
    MixedStep verify epilogue).

    logits_rows [S, K+1, V]: the target's logits at each span's K+1
    verify rows (row j predicts the token at position
    ``base_pos[s] + j``).  draft_tokens [S, K] int32 (garbage beyond
    ``n_draft``); n_draft [S] int32 in [0, K] — 0 marks a plain decode
    span that just samples row 0.  q_rows: [S, K, V] draft filtered
    probabilities (None = greedy-only verification).  Returns
    ``(n_acc [S] int32, token [S] int32)``: the count of accepted
    draft tokens and the emitted correction/bonus token sampled from
    the residual (rejection) or from ``p_{n_acc}`` (full acceptance) —
    the same formula, since a bonus row has ``q = 0``.
    """
    lf = logits_rows.astype(jnp.float32)
    S, K1, V = lf.shape
    K = K1 - 1
    tgt_arg = jnp.argmax(lf, axis=-1).astype(jnp.int32)        # [S, K+1]
    jidx = jnp.arange(K, dtype=jnp.int32)
    in_range = jidx[None, :] < n_draft[:, None]
    ok_greedy = tgt_arg[:, :K] == draft_tokens

    if q_rows is not None:
        # target filtered distributions, one per verify row
        pf = jax.vmap(lambda rows, t, k, p: jax.vmap(
            lambda l: jax.nn.softmax(_filter_row(l, t, k, p)))(rows)
        )(lf, temps, top_ks, top_ps)                           # [S,K+1,V]
        q = q_rows
        d_idx = jnp.clip(draft_tokens, 0, V - 1)[..., None]
        p_d = jnp.take_along_axis(pf[:, :K], d_idx, -1)[..., 0]
        q_d = jnp.take_along_axis(q, d_idx, -1)[..., 0]

        def u_row(seed, bp):
            return jax.vmap(lambda j: jax.random.uniform(
                _row_key(seed, bp + j, _TAG_ACCEPT)))(jidx)

        u = jax.vmap(u_row)(seeds, base_pos)                   # [S, K]
        ok_samp = u * jnp.maximum(q_d, jnp.float32(1e-30)) < p_d
        ok = jnp.where((temps > 0)[:, None], ok_samp, ok_greedy)
    else:
        ok = ok_greedy
    ok = ok & in_range
    chain = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(chain, axis=1).astype(jnp.int32)           # [S]

    e_idx = n_acc[:, None]
    e_greedy = jnp.take_along_axis(tgt_arg, e_idx, 1)[:, 0]
    if q_rows is None:
        return n_acc, e_greedy

    row_idx = jnp.broadcast_to(n_acc[:, None, None], (S, 1, V))
    p_row = jnp.take_along_axis(pf, row_idx, 1)[:, 0]          # [S, V]
    # bonus rows (n_acc == n_draft) resample from p directly: pad q
    # with a zero row so the residual formula covers both cases, and
    # zero any row whose index would alias the NEXT round's q
    q_pad = jnp.concatenate([q, jnp.zeros((S, 1, V), jnp.float32)], 1)
    q_row = jnp.take_along_axis(q_pad, row_idx[:, :, :V], 1)[:, 0]
    q_row = jnp.where((n_acc >= n_draft)[:, None], jnp.float32(0.0),
                      q_row)
    w = jnp.maximum(p_row - q_row, jnp.float32(0.0))
    w_sum = jnp.sum(w, axis=-1, keepdims=True)
    w = jnp.where(w_sum > 0, w, p_row)     # numeric guard: p==q exactly

    def g_row(seed, bp, na):
        return jax.random.gumbel(_row_key(seed, bp + na, _TAG_RESIDUAL),
                                 (V,), jnp.float32)

    g = jax.vmap(g_row)(seeds, base_pos, n_acc)
    logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-38)), -jnp.inf)
    e_samp = jnp.argmax(logw + g, axis=-1).astype(jnp.int32)
    return n_acc, jnp.where(temps > 0, e_samp, e_greedy)
