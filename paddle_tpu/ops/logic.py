"""Comparison / logic helper ops (beyond the elementwise tables in math.py).

Parity: python/paddle/tensor/logic.py (reference).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .registry import register_op
from ._helpers import as_value, wrap, targ


@register_op("allclose", category="logic", tensor_method=True)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        "allclose",
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan),
        (x, targ(y)))


@register_op("isclose", category="logic", tensor_method=True)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        "isclose",
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                 equal_nan=equal_nan),
        (x, targ(y)))


@register_op("equal_all", category="logic", tensor_method=True)
def equal_all(x, y, name=None):
    return apply_op("equal_all",
                    lambda a, b: jnp.array_equal(a, b), (x, targ(y)))


@register_op("is_empty", category="logic", tensor_method=True)
def is_empty(x, name=None):
    return wrap(jnp.asarray(as_value(x).size == 0))


@register_op("is_tensor", category="logic")
def is_tensor(x):
    return isinstance(x, Tensor)


@register_op("in_dynamic_mode", category="logic")
def in_dynamic_mode():
    """Parity: paddle.in_dynamic_mode — False while enable_static() is
    active."""
    try:
        from ..static import in_static_mode
        return not in_static_mode()
    except ImportError:
        return True
