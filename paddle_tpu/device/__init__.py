"""paddle.device — device management + memory accounting.

Parity: python/paddle/device/ (reference — set_device/get_device,
device/cuda/* memory stats backed by paddle/fluid/memory/stats.h
DEVICE_MEMORY_STAT macros, streams/events).

TPU-native: allocation is PJRT's job, so stats come from the PJRT
``Device.memory_stats()`` counters (bytes_in_use / peak_bytes_in_use on
TPU).  Backends without allocator telemetry (XLA CPU) fall back to
summing live on-device arrays, with the peak tracked at query points.
Streams/events collapse to XLA's async dispatch: synchronize =
drain-and-block.
"""
from __future__ import annotations

from typing import Optional

import jax

from ..core.device import (CPUPlace, TPUPlace, CustomPlace, get_device,
                           set_device, is_compiled_with_tpu)


def is_compiled_with_cuda() -> bool:
    return any(d.platform == "gpu" for d in jax.devices())


def is_compiled_with_xpu() -> bool:
    return False

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_available_device", "get_available_custom_device",
           "device_count", "synchronize", "memory_allocated",
           "max_memory_allocated", "memory_reserved",
           "max_memory_reserved", "reset_peak_memory_stats",
           "memory_stats",
           "cuda", "CPUPlace", "TPUPlace", "CustomPlace",
           "Stream", "Event", "current_stream", "stream_guard"]


def _device(dev: Optional[int] = None):
    devs = jax.local_devices()
    return devs[dev or 0]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu", "tpu")]


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        return jax.device_count()
    return sum(1 for d in jax.devices() if d.platform == device_type)


def synchronize(device=None):
    """Block until all dispatched device work is done."""
    jax.effects_barrier()
    for arr in jax.live_arrays():
        try:
            arr.block_until_ready()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# memory stats (reference: paddle/fluid/memory/stats.h — peak/current per
# device, surfaced as paddle.device.cuda.max_memory_allocated)
# ---------------------------------------------------------------------------
_PEAK_FALLBACK = {}     # device index -> peak bytes seen at query points
_PEAK_BASELINE = {}     # device index -> PJRT peak counter at last reset


def memory_stats(device=None) -> dict:
    """The raw PJRT allocator counters for one device
    (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` ... on
    TPU) — SURVEY §5.5 memory-stat parity.  Backends without allocator
    telemetry (XLA CPU) and failed/uninitialized backends return ``{}``
    instead of raising, so telemetry code can poll unconditionally."""
    try:
        return _dev_stats(_device(device))
    except Exception:                                 # noqa: BLE001
        return {}


def _dev_stats(d) -> dict:
    """Stats for an already-resolved device; {} when none/failed."""
    try:
        stats = d.memory_stats()
    except Exception:                                 # noqa: BLE001
        return {}
    return dict(stats) if stats else {}


def _live_bytes(dev) -> int:
    total = 0
    for arr in jax.live_arrays():
        try:
            for shard in arr.addressable_shards:
                if shard.device == dev:
                    total += shard.data.nbytes
        except Exception:
            pass
    return total


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the device (parity:
    paddle.device.cuda.memory_allocated).  Never raises: a backend
    without stats falls back to summing live arrays, and a missing/
    broken backend reports 0."""
    try:
        d = _device(device)
    except Exception:                                 # noqa: BLE001
        return 0
    stats = _dev_stats(d)
    if stats and "bytes_in_use" in stats:
        cur = int(stats["bytes_in_use"])
    else:
        cur = _live_bytes(d)
    key = d.id
    _PEAK_FALLBACK[key] = max(_PEAK_FALLBACK.get(key, 0), cur)
    return cur


def max_memory_allocated(device=None) -> int:
    """Peak allocated bytes (parity: paddle.device.cuda.max_memory_allocated).

    On backends without allocator counters the peak is tracked at query
    points — call memory_allocated() at the places you care about.  PJRT
    exposes no peak-reset, so after reset_peak_memory_stats() the device
    counter only counts if it rises above its value at reset; otherwise
    current usage sampled at query points is the post-reset peak.
    Never raises; 0 when no backend is available."""
    try:
        d = _device(device)
    except Exception:                                 # noqa: BLE001
        return 0
    stats = _dev_stats(d)
    if stats and "peak_bytes_in_use" in stats:
        peak = int(stats["peak_bytes_in_use"])
        base = _PEAK_BASELINE.get(d.id)
        if base is None:
            return peak
        sampled = max(_PEAK_FALLBACK.get(d.id, 0),
                      int(stats.get("bytes_in_use", 0)))
        _PEAK_FALLBACK[d.id] = sampled
        return peak if peak > base else sampled
    memory_allocated(device)
    return _PEAK_FALLBACK.get(d.id, 0)


def memory_reserved(device=None) -> int:
    stats = memory_stats(device)
    if stats:
        for k in ("bytes_reserved", "pool_bytes", "bytes_limit"):
            if k in stats:
                return int(stats[k])
    return memory_allocated(device)


def max_memory_reserved(device=None) -> int:
    return max(memory_reserved(device), max_memory_allocated(device))


def reset_peak_memory_stats(device=None):
    try:
        d = _device(device)
    except Exception:                                 # noqa: BLE001
        return
    _PEAK_FALLBACK[d.id] = 0
    stats = _dev_stats(d)
    if "peak_bytes_in_use" in stats:
        _PEAK_BASELINE[d.id] = int(stats["peak_bytes_in_use"])


def reset_max_memory_allocated(device=None):
    reset_peak_memory_stats(device)


def reset_max_memory_reserved(device=None):
    reset_peak_memory_stats(device)


# ---------------------------------------------------------------------------
# streams/events (XLA dispatch is already async; sync points map to
# block_until_ready)
# ---------------------------------------------------------------------------
class Stream:
    """Parity: paddle.device.Stream.  XLA runs one async dispatch stream
    per device; explicit streams are ordering no-ops kept for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev


class Event:
    """Parity: paddle.device.Event."""

    def __init__(self, device=None, enable_timing=False, blocking=False):
        self._recorded = False
        import time
        self._time = time.perf_counter

    def record(self, stream=None):
        self._recorded = True
        self._t0 = self._time()

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event) -> float:
        return max(0.0, (getattr(end_event, "_t0", self._time())
                         - getattr(self, "_t0", 0.0)) * 1000.0)


_CURRENT_STREAM = Stream()


def current_stream(device=None) -> Stream:
    return _CURRENT_STREAM


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


# ---------------------------------------------------------------------------
# paddle.device.cuda namespace (reference API surface; maps to the
# current accelerator)
# ---------------------------------------------------------------------------
class _CudaNamespace:
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        n = device_count("gpu")
        return n if n else device_count("tpu")

    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_reserved = staticmethod(memory_reserved)
    reset_max_memory_allocated = staticmethod(reset_max_memory_allocated)
    reset_max_memory_reserved = staticmethod(reset_max_memory_reserved)
    synchronize = staticmethod(synchronize)
    current_stream = staticmethod(current_stream)
    stream_guard = staticmethod(stream_guard)

    @staticmethod
    def get_device_properties(device=None):
        d = _device(device)
        class _Props:
            name = d.device_kind
            total_memory = (d.memory_stats() or {}).get("bytes_limit", 0)
            major, minor = 0, 0
            multi_processor_count = 1
        return _Props()

    @staticmethod
    def empty_cache():
        import gc
        gc.collect()

    @staticmethod
    def get_device_name(device=None):
        """Parity: device/cuda get_device_name — the accelerator kind
        string (TPU kind here, e.g. 'TPU v5 lite')."""
        return _device(device).device_kind

    @staticmethod
    def get_device_capability(device=None):
        """Parity: get_device_capability — (major, minor).  CUDA compute
        capability has no TPU analog; the TPU generation number is the
        meaningful major version."""
        kind = _device(device).device_kind
        import re as _re
        m = _re.search(r"v(\d+)", kind)
        return (int(m.group(1)) if m else 0, 0)


cuda = _CudaNamespace()


def get_cudnn_version():
    """Parity: paddle.device.get_cudnn_version — None when not built
    with cuDNN (always, on the TPU stack)."""
    return None


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    """The graph compiler here is XLA, not CINN."""
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    """Distributed (collectives over ICI/DCN) is always built in."""
    return True


def is_compiled_with_custom_device(device_type: str = None) -> bool:
    """PJRT plugins are the custom-device mechanism; 'tpu' (and the
    axon tunnel) count."""
    import jax
    try:
        plats = {d.platform for d in jax.devices()}
    except RuntimeError:
        return False
    if device_type is None:
        return bool(plats - {"cpu", "gpu"})
    return device_type in plats


def get_all_custom_device_type():
    import jax
    try:
        return sorted({d.platform for d in jax.devices()}
                      - {"cpu", "gpu"})
    except RuntimeError:
        return []


class XPUPlace:
    """Parity name (device/__init__ XPUPlace): Kunlun XPU hardware is
    not present on a TPU stack; constructing one is an error, as on any
    paddle build without XPU support."""

    def __init__(self, dev_id=0):
        raise RuntimeError(
            "XPUPlace is unavailable: this framework targets TPU "
            "devices (use paddle.TPUPlace / CPUPlace)")


class IPUPlace:
    """Parity name (device/__init__ IPUPlace); same contract as
    XPUPlace on a non-IPU build."""

    def __init__(self):
        raise RuntimeError(
            "IPUPlace is unavailable: this framework targets TPU "
            "devices (use paddle.TPUPlace / CPUPlace)")


def set_stream(stream=None):
    """Parity: device.set_stream.  XLA orders work on a single device
    stream by data dependence; the call validates the handle and
    returns the previous (current) stream."""
    prev = current_stream()
    if stream is not None and not isinstance(stream, Stream):
        raise TypeError(f"set_stream expects a Stream, got {type(stream)}")
    return prev


__all__ += ["get_cudnn_version", "XPUPlace", "IPUPlace",
            "is_compiled_with_ipu", "is_compiled_with_cinn",
            "is_compiled_with_rocm", "is_compiled_with_distribute",
            "is_compiled_with_custom_device",
            "get_all_custom_device_type", "set_stream"]

