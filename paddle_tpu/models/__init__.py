"""Model zoo.

Parity intent: the reference ecosystem's model families (PaddleNLP llama/
ernie, PaddleClas resnet, BASELINE.json configs) — here implemented
natively on paddle_tpu layers with mesh-shardable parameters.
"""
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM,
                    LlamaPretrainingCriterion, llama_tiny_config,
                    llama_7b_config)
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, \
    resnet152
from .bert import BertConfig, BertModel, BertForPretraining, \
    BertForSequenceClassification
from .gpt import GPTConfig, GPTModel, GPTForCausalLM
from .qwen import (Qwen2Config, Qwen2Model, Qwen2ForCausalLM,
                   Qwen2PretrainingCriterion, qwen2_tiny_config)
from .mixtral import (MixtralConfig, MixtralModel, MixtralForCausalLM,
                      MixtralPretrainingCriterion, MixtralSparseMoeBlock,
                      mixtral_tiny_config, shard_mixtral)
