"""ResNet family (BASELINE.json configs[0]: ResNet-50 ImageNet).

Parity: python/paddle/vision/models/resnet.py (reference).
"""
from __future__ import annotations

from typing import List, Optional, Type

from ..nn.layer_base import Layer
from ..nn.layers import (Conv2D, BatchNorm2D, ReLU, MaxPool2D,
                         AdaptiveAvgPool2D, Linear, Sequential)
from ..nn import functional as F


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 base_width=64, groups=1):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, planes * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(planes * 4)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    """Parity: paddle.vision.models.ResNet."""

    def __init__(self, block, depth_cfg: List[int], num_classes=1000,
                 with_pool=True, in_channels=3, width=64, groups=1):
        super().__init__()
        self.inplanes = 64
        # width=64*2 -> wide resnet (reference ResNet(..., width=128));
        # groups>1 + width=4/8 -> resnext (cardinality x bottleneck width)
        self._base_width = width
        self._groups = groups
        self.conv1 = Conv2D(in_channels, 64, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)
        self.num_classes = num_classes

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        if not issubclass(block, BottleneckBlock) \
                and (self._base_width != 64 or self._groups != 1):
            raise ValueError(
                "width != 64 / groups != 1 require BottleneckBlock "
                "architectures (resnet50+); BasicBlock has no width knob")
        kw = {"base_width": self._base_width, "groups": self._groups} \
            if issubclass(block, BottleneckBlock) else {}
        layers = [block(self.inplanes, planes, stride, downsample, **kw)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **kw))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


def resnet18(pretrained=False, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(pretrained=False, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], **kw)


def resnet101(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], **kw)


def resnet152(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], **kw)


def wide_resnet50_2(pretrained=False, **kw):
    """Parity: paddle.vision.models.wide_resnet50_2 (resnet.py:66)."""
    return ResNet(BottleneckBlock, [3, 4, 6, 3], width=64 * 2, **kw)


def wide_resnet101_2(pretrained=False, **kw):
    """Parity: paddle.vision.models.wide_resnet101_2 (resnet.py:70)."""
    return ResNet(BottleneckBlock, [3, 4, 23, 3], width=64 * 2, **kw)


def _resnext(depth_cfg, groups, width, **kw):
    return ResNet(BottleneckBlock, depth_cfg, groups=groups, width=width,
                  **kw)


def resnext50_32x4d(pretrained=False, **kw):
    """Parity: paddle.vision.models.resnext50_32x4d (resnext.py)."""
    return _resnext([3, 4, 6, 3], 32, 4, **kw)


def resnext50_64x4d(pretrained=False, **kw):
    return _resnext([3, 4, 6, 3], 64, 4, **kw)


def resnext101_32x4d(pretrained=False, **kw):
    return _resnext([3, 4, 23, 3], 32, 4, **kw)


def resnext101_64x4d(pretrained=False, **kw):
    return _resnext([3, 4, 23, 3], 64, 4, **kw)


def resnext152_32x4d(pretrained=False, **kw):
    return _resnext([3, 8, 36, 3], 32, 4, **kw)


def resnext152_64x4d(pretrained=False, **kw):
    return _resnext([3, 8, 36, 3], 64, 4, **kw)
