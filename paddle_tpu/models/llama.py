"""Llama model family — the flagship pretraining model.

Parity intent: PaddleNLP's llama modeling on the reference stack
(BASELINE.json configs[3]/[4]: Llama-2-7B/13B pretrain with sharding
stage-3 + tensor parallel; north star >50% MFU on v5p).

TPU-native design:
- bf16 parameters/activations by default; fp32 RMSNorm statistics and
  softmax logits.
- attention via scaled_dot_product_attention -> Pallas flash kernel on TPU.
- rotary embeddings via the fused rope op.
- mesh-shardable: ``shard_llama`` annotates params for tp/fsdp axes
  (megatron layout: qkv/gate/up column-sharded, o/down row-sharded,
  embeddings vocab-sharded, everything FSDP-sharded on the remaining axis)
  — GSPMD turns these into the Megatron collective pattern over ICI.
- sequence parallelism: the "sep" mesh axis shards the sequence dim of
  activations (long-context path; ring attention kernel in
  ops/pallas_kernels.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layers import Linear, Embedding, RMSNorm, LayerList
from ..incubate.nn.functional import fused_rotary_position_embedding


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    dtype: str = "float32"
    use_flash_attention: bool = True
    recompute: bool = False
    sequence_parallel: bool = False
    # "ring" (k/v rotation over ICI) or "ulysses" (all-to-all head swap)
    seq_parallel_mode: str = "ring"
    # qkv biases (qwen2-family architecture; llama proper has none)
    attention_bias: bool = False


def llama_7b_config(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_tiny_config(**kw) -> LlamaConfig:
    cfg = dict(vocab_size=1024, hidden_size=128, intermediate_size=352,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=256)
    cfg.update(kw)
    return LlamaConfig(**cfg)


class LlamaMLP(Layer):
    """SwiGLU MLP (gate/up column-parallel, down row-parallel under TP)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.gate_proj = Linear(config.hidden_size,
                                config.intermediate_size,
                                weight_attr=_attr(init), bias_attr=False)
        self.up_proj = Linear(config.hidden_size, config.intermediate_size,
                              weight_attr=_attr(init), bias_attr=False)
        self.down_proj = Linear(config.intermediate_size,
                                config.hidden_size,
                                weight_attr=_attr(init), bias_attr=False)

    def forward(self, x):
        from ..nn.functional.activation import swiglu
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class _Attr:
    def __init__(self, initializer):
        self.initializer = initializer
        self.name = None


def _attr(init):
    return _Attr(init)


class LlamaAttention(Layer):
    """GQA attention with rotary embeddings and flash attention."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        init = I.Normal(0.0, config.initializer_range)
        h = config.hidden_size
        sp_mode = getattr(config, "seq_parallel_mode", "ring")
        if sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_parallel_mode must be 'ring' or 'ulysses', got "
                f"{sp_mode!r}")
        qkv_bias = bool(getattr(config, "attention_bias", False))
        self.q_proj = Linear(h, self.num_heads * self.head_dim,
                             weight_attr=_attr(init), bias_attr=qkv_bias)
        self.k_proj = Linear(h, self.num_kv_heads * self.head_dim,
                             weight_attr=_attr(init), bias_attr=qkv_bias)
        self.v_proj = Linear(h, self.num_kv_heads * self.head_dim,
                             weight_attr=_attr(init), bias_attr=qkv_bias)
        self.o_proj = Linear(self.num_heads * self.head_dim, h,
                             weight_attr=_attr(init), bias_attr=False)

    def _ring_axis(self):
        """Long-context path: when sequence_parallel is on and the hybrid
        mesh has a sep axis > 1, attention runs as ring attention with
        k/v rotating over that axis (collective-permute on ICI)."""
        if not self.config.sequence_parallel:
            return None
        from ..distributed.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is not None and hcg.get_sep_parallel_world_size() > 1:
            return "sep"
        return None

    def forward(self, x, attn_mask=None, cache=None, position_offset=0):
        B, S = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape([B, S, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([B, S, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([B, S, self.num_kv_heads, self.head_dim])

        # training fast path: neox rope fused INTO the flash kernels
        # (no rope ops in the XLA graph; see pallas_kernels.
        # flash_attention_rope).  Cache/mask/sequence-parallel configs
        # take the general path below.
        if (cache is None and attn_mask is None and not position_offset
                and self._ring_axis() is None):
            from ..ops.pallas_kernels import flash_attention_rope
            if self.num_kv_heads != self.num_heads:
                rep = self.num_heads // self.num_kv_heads
                from ..ops.manipulation import repeat_interleave
                k = repeat_interleave(k, rep, axis=2)
                v = repeat_interleave(v, rep, axis=2)
            out = flash_attention_rope(
                q, k, v, rotary_base=self.config.rope_theta,
                is_causal=True)
            out = out.reshape([B, S, self.num_heads * self.head_dim])
            return self.o_proj(out)

        position_ids = None
        if position_offset:
            position_ids = np.arange(position_offset,
                                     position_offset + S, dtype=np.int32)
        q, k, _ = fused_rotary_position_embedding(
            q, k, position_ids=position_ids,
            rotary_emb_base=self.config.rope_theta)

        if cache is not None and cache[0] is not None \
                and cache[0].shape[1] > 0:
            from ..ops.manipulation import concat
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
        new_cache = (k, v)   # pre-GQA-repeat: Hkv heads, reusable next step

        # GQA: repeat kv heads
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            from ..ops.manipulation import repeat_interleave
            k = repeat_interleave(k, rep, axis=2)
            v = repeat_interleave(v, rep, axis=2)

        # bottom-right-aligned causal covers both prefill and decode
        # (S==1 rows see the whole cache)
        is_causal = attn_mask is None
        ring_axis = self._ring_axis() if (is_causal and cache is None) \
            else None
        if ring_axis is not None:
            from ..ops.pallas_kernels import sdpa_ring, sdpa_ulysses
            from ..distributed.topology import \
                get_hybrid_communicate_group
            sp_fn = sdpa_ulysses if getattr(
                self.config, "seq_parallel_mode", "ring") == "ulysses" \
                else sdpa_ring
            out = sp_fn(q, k, v,
                        get_hybrid_communicate_group().mesh,
                        axis_name=ring_axis, is_causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=is_causal)
        out = out.reshape([B, S, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if cache is not None:
            return out, new_cache
        return out


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)
        self._recompute = config.recompute

    def _block(self, x, attn_mask=None):
        h = x + self.self_attn(self.input_layernorm(x), attn_mask)
        return h + self.mlp(self.post_attention_layernorm(h))

    def forward_with_cache(self, x, cache, position_offset,
                           attn_mask=None):
        attn, new_cache = self.self_attn(
            self.input_layernorm(x), attn_mask, cache=cache,
            position_offset=position_offset)
        h = x + attn
        return h + self.mlp(self.post_attention_layernorm(h)), new_cache

    def forward(self, x, attn_mask=None):
        if self._recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            return recompute(self._block, x, attn_mask)
        return self._block(x, attn_mask)


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=_attr(I.Normal(0.0, config.initializer_range)))
        self.layers = LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, caches=None,
                position_offset=0):
        h = self.embed_tokens(input_ids)
        if self.config.dtype == "bfloat16":
            h = h.astype("bfloat16")
        if caches is None:
            for layer in self.layers:
                h = layer(h, attn_mask)
            return self.norm(h)
        new_caches = []
        for layer, cache in zip(self.layers, caches):
            h, c = layer.forward_with_cache(h, cache, position_offset,
                                            attn_mask)
            new_caches.append(c)
        return self.norm(h), new_caches


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=_attr(I.Normal(0.0, config.initializer_range)),
                bias_attr=False)

    def forward(self, input_ids, attn_mask=None, caches=None,
                position_offset=0):
        if caches is None:
            h = self.llama(input_ids, attn_mask)
        else:
            h, caches = self.llama(input_ids, attn_mask, caches,
                                   position_offset)
        if self.lm_head is None:
            from ..ops.linalg import matmul
            logits = matmul(h, self.llama.embed_tokens.weight,
                            transpose_y=True)
        else:
            logits = self.lm_head(h)
        if caches is not None:
            return logits, caches
        return logits

    def generate(self, input_ids, max_new_tokens=16, temperature=1.0,
                 top_p=0.0, eos_token_id=None, seed=0):
        """Autoregressive decode with per-layer KV caches (the serving
        path; parity with the reference's generation loop over
        masked/block attention kernels).  top_p=0 -> greedy."""
        import numpy as np_
        from ..ops.manipulation import concat
        from ..autograd.tape import no_grad
        n_layers = self.config.num_hidden_layers
        with no_grad():
            caches = [(None, None)] * n_layers
            logits, caches = self.forward(input_ids, caches=caches)
            out_ids = [input_ids]
            cur_len = input_ids.shape[1]
            for step in range(max_new_tokens):
                last = logits[:, -1, :]
                if top_p and top_p > 0.0:
                    from ..ops.extras import top_p_sampling
                    if temperature != 1.0:
                        last = last / temperature
                    probs = F.softmax(last, axis=-1)
                    ps = np_.full((probs.shape[0],), float(top_p),
                                  np_.float32)
                    _, nxt = top_p_sampling(probs, ps, seed=seed + step)
                    nxt = nxt.reshape([-1, 1])
                else:
                    nxt = last.argmax(-1).reshape([-1, 1])
                out_ids.append(nxt)
                if eos_token_id is not None:
                    if bool(np_.all(np_.asarray(nxt._value)
                                    == eos_token_id)):
                        break
                if step < max_new_tokens - 1:    # last token needs no fwd
                    logits, caches = self.forward(
                        nxt, caches=caches, position_offset=cur_len)
                    cur_len += 1
            return concat(out_ids, axis=1)


class LlamaPretrainingCriterion(Layer):
    """Shift-by-one LM loss with fp32 softmax (PaddleNLP parity)."""

    def __init__(self, config: Optional[LlamaConfig] = None,
                 ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        # logits [B, S, V]; labels [B, S] — predict token t+1.
        # Shift the LABELS (roll left, mask the last position with
        # ignore_index) instead of slicing the logits: numerically
        # identical, but avoids duplicating the [B, S, V] logits tensor
        # (~1 GB at llama-7B scale) and keeps S a tile-aligned 2^n.
        from ..ops.manipulation import reshape, concat
        from ..ops.creation import full
        B = labels.shape[0]
        tail = full([B, 1], self.ignore_index, dtype=labels.dtype)
        shift_labels = concat([labels[:, 1:], tail], axis=1)
        V = logits.shape[-1]
        return F.cross_entropy(
            reshape(logits, [-1, V]),
            reshape(shift_labels, [-1]),
            ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# sharding recipe (tp/fsdp/dp/sep axes)
# ---------------------------------------------------------------------------
def axis_placements(mesh, **axis_dims):
    """Placement list for ``mesh`` from axis-name -> tensor-dim pairs
    (axes absent from the mesh, size-1 axes, and None dims replicate).
    Shared by the per-model sharding recipes (shard_llama,
    shard_mixtral, ...)."""
    from ..distributed.process_mesh import Shard, Replicate

    names = mesh.dim_names
    pl = [Replicate() for _ in names]
    for axis, dim in axis_dims.items():
        if dim is None or axis not in names \
                or mesh.get_dim_size(axis) <= 1:
            continue
        pl[names.index(axis)] = Shard(dim)
    return pl


def shard_llama(model: LlamaForCausalLM, mesh, tp_axis="model",
                fsdp_axis="sharding"):
    """Annotate parameters with the Megatron/FSDP layout over ``mesh``:

    - qkv/gate/up: column-sharded on tp (out dim), fsdp on in dim
    - o/down: row-sharded on tp (in dim), fsdp on out dim
    - embeddings + lm_head: vocab-sharded on tp
    - norms: replicated
    GSPMD derives the collective pattern; on a pod the tp axis should map
    to the innermost ICI dim.
    """
    from ..distributed.api import shard_param_

    def placements(tp_dim=None, fsdp_dim=None):
        return axis_placements(mesh, **{tp_axis: tp_dim,
                                        fsdp_axis: fsdp_dim})

    emb = model.llama.embed_tokens.weight
    shard_param_(emb, mesh, placements(tp_dim=0, fsdp_dim=1))
    if model.lm_head is not None:
        shard_param_(model.lm_head.weight, mesh,
                     placements(tp_dim=1, fsdp_dim=0))
    for layer in model.llama.layers:
        a = layer.self_attn
        for lin in (a.q_proj, a.k_proj, a.v_proj):
            shard_param_(lin.weight, mesh, placements(tp_dim=1, fsdp_dim=0))
        shard_param_(a.o_proj.weight, mesh, placements(tp_dim=0,
                                                       fsdp_dim=1))
        m = layer.mlp
        shard_param_(m.gate_proj.weight, mesh,
                     placements(tp_dim=1, fsdp_dim=0))
        shard_param_(m.up_proj.weight, mesh,
                     placements(tp_dim=1, fsdp_dim=0))
        shard_param_(m.down_proj.weight, mesh,
                     placements(tp_dim=0, fsdp_dim=1))
    return model


def llama_truncated_draft(model: LlamaForCausalLM,
                          num_layers: int = 1) -> LlamaForCausalLM:
    """Layer-truncated self-speculative draft: the SAME config cut to
    the first ``num_layers`` decoder layers, with the embedding, those
    layers, the final norm and the LM head COPIED from the target
    (early-exit drafting).  Residual blocks are near-identity, so the
    truncated model's argmax tracks the full model closely — a cheap,
    training-free draft whose acceptance rate the speculative-decoding
    bench measures (``tools/bench_serving.py --speculative``)."""
    from dataclasses import replace
    cfg = model.config
    if not (0 < num_layers < cfg.num_hidden_layers):
        raise ValueError(
            "draft must be a strict layer truncation: 0 < num_layers="
            "%d < %d" % (num_layers, cfg.num_hidden_layers))
    draft = LlamaForCausalLM(replace(cfg, num_hidden_layers=num_layers))
    if cfg.dtype == "bfloat16":
        draft.bfloat16()
    draft.eval()
    src = model.state_dict()
    keep = set(draft.state_dict())
    draft.set_state_dict({k: v for k, v in src.items() if k in keep})
    return draft


def llama_flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """6*N + attention correction (BASELINE.md convention)."""
    n_params = param_count(config)
    attn = 12 * config.num_hidden_layers * config.hidden_size * seq_len
    return 6.0 * n_params + attn


def param_count(config: LlamaConfig) -> int:
    h, i, v = config.hidden_size, config.intermediate_size, config.vocab_size
    L = config.num_hidden_layers
    kv = config.num_key_value_heads * (h // config.num_attention_heads)
    per_layer = h * h + 2 * h * kv + h * h + 3 * h * i + 2 * h
    emb = v * h * (1 if config.tie_word_embeddings else 2)
    return L * per_layer + emb + h
