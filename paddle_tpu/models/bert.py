"""BERT/ERNIE-class encoder (BASELINE.json configs[1]: BERT-base
fine-tune under data parallelism)."""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers import (Embedding, Linear, LayerNorm, Dropout, Tanh)
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer
from ..nn import functional as F


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size)
        self.token_type_embeddings = Embedding(c.type_vocab_size,
                                               c.hidden_size)
        self.layer_norm = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        from ..ops.creation import arange, zeros_like
        from ..ops.manipulation import unsqueeze, expand
        S = input_ids.shape[1]
        pos = unsqueeze(arange(S, dtype="int64"), 0)
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        h = self.word_embeddings(input_ids) \
            + self.position_embeddings(pos) \
            + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(h))


class BertPooler(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.dense = Linear(c.hidden_size, c.hidden_size)
        self.activation = Tanh()

    def forward(self, hidden):
        return self.activation(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob)
        self.encoder = TransformerEncoder(enc_layer,
                                          config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            from ..ops.manipulation import unsqueeze
            m = unsqueeze(unsqueeze(attention_mask, 1), 1)
            mask = (1.0 - m.astype("float32")) * -1e9
        else:
            mask = None
        h = self.encoder(h, mask)
        return h, self.pooler(h)


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForPretraining(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.mlm_head = Linear(config.hidden_size, config.vocab_size)
        self.nsp_head = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.mlm_head(seq), self.nsp_head(pooled)
