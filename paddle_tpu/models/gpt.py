"""GPT-class decoder (ERNIE-Bot-scale 4D-parallel config family,
BASELINE.json configs[4])."""
from __future__ import annotations

from dataclasses import dataclass

from ..nn.layer_base import Layer
from ..nn.layers import Embedding, Linear, LayerNorm, Dropout, LayerList
from ..nn.transformer import MultiHeadAttention
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5


class GPTBlock(Layer):
    def __init__(self, c: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.attn = MultiHeadAttention(c.hidden_size, c.num_attention_heads,
                                       c.attention_probs_dropout_prob)
        self.ln_2 = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.fc1 = Linear(c.hidden_size, c.intermediate_size)
        self.fc2 = Linear(c.intermediate_size, c.hidden_size)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, x, mask=None):
        h = self.ln_1(x)
        B, S = h.shape[0], h.shape[1]
        q = self.attn.q_proj(h).reshape([B, S, self.attn.num_heads,
                                         self.attn.head_dim])
        k = self.attn.k_proj(h).reshape([B, S, self.attn.num_heads,
                                         self.attn.head_dim])
        v = self.attn.v_proj(h).reshape([B, S, self.attn.num_heads,
                                         self.attn.head_dim])
        a = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                           training=self.training)
        a = self.attn.out_proj(a.reshape([B, S, -1]))
        x = x + self.dropout(a)
        m = self.fc2(F.gelu(self.fc1(self.ln_2(x))))
        return x + self.dropout(m)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = Embedding(config.vocab_size, config.hidden_size)
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size)
        self.drop = Dropout(config.hidden_dropout_prob)
        self.blocks = LayerList([GPTBlock(config)
                                 for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids):
        from ..ops.creation import arange
        from ..ops.manipulation import unsqueeze
        S = input_ids.shape[1]
        pos = unsqueeze(arange(S, dtype="int64"), 0)
        h = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            h = blk(h)
        return self.ln_f(h)


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)

    def forward(self, input_ids):
        h = self.gpt(input_ids)
        from ..ops.linalg import matmul
        return matmul(h, self.gpt.wte.weight, transpose_y=True)
