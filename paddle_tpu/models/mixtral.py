"""Mixtral: sparse mixture-of-experts Llama (top-k routed SwiGLU experts).

Parity intent: the reference ecosystem's MoE LLM family (PaddleNLP
mixtral; reference fused-MoE kernels paddle/phi/kernels/fusion/ and
incubate MoELayer python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 with global_scatter/global_gather all-to-all
:119,:167).

TPU-native design: expert weights are BATCHED [E, ...] parameters so the
whole expert bank runs as single einsums on the MXU (no per-expert
python loop), and routing is GShard-style dense dispatch into capacity
buffers.  Under a mesh, sharding the E dim places experts on different
devices and GSPMD emits the all-to-all dispatch/combine pair the
reference implements with NCCL collectives.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn.layers import Linear, LayerList
from ..nn import initializer as I
from ..ops._helpers import targ
from .llama import (LlamaConfig, LlamaAttention, LlamaForCausalLM,
                    RMSNorm, _attr, LlamaPretrainingCriterion)


@dataclass
class MixtralConfig(LlamaConfig):
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02
    expert_capacity_factor: float = 2.0


class MixtralSparseMoeBlock(Layer):
    """Top-k routed SwiGLU expert bank with batched weights.

    Parity: the reference MoELayer + fused_ec_moe
    (python/paddle/incubate/nn/functional/fused_ec_moe.py) — here one
    dense-dispatch einsum pipeline: route -> capacity buffers [E, C, D]
    -> three batched expert einsums -> weighted combine."""

    def __init__(self, config: MixtralConfig):
        super().__init__()
        D = config.hidden_size
        M = config.intermediate_size
        E = config.num_local_experts
        self.top_k = config.num_experts_per_tok
        self.num_experts = E
        self.capacity_factor = config.expert_capacity_factor
        self.aux_coef = config.router_aux_loss_coef
        init = I.Normal(0.0, config.initializer_range)
        self.gate = Linear(D, E, weight_attr=_attr(init), bias_attr=False)
        self.w_gate = self.create_parameter([E, D, M], attr=_attr(init))
        self.w_up = self.create_parameter([E, D, M], attr=_attr(init))
        self.w_down = self.create_parameter([E, M, D], attr=_attr(init))
        self.l_aux = None

    def forward(self, x):
        orig_shape = x.shape
        from ..ops.manipulation import reshape
        flat = reshape(x, [-1, x.shape[-1]])
        n_tokens = int(flat.shape[0])
        capacity = max(1, int(self.capacity_factor * n_tokens *
                              self.top_k / self.num_experts))
        E, k = self.num_experts, self.top_k

        def fn(v, gw, wg, wu, wd):
            from ..ops.moe_gate import (topk_gate, assignment_slots,
                                        dispatch_to_buffers,
                                        grouped_expert_swiglu,
                                        combine_from_buffers)
            logits = (v.astype(jnp.float32)
                      @ gw.astype(jnp.float32))          # [N, E]
            top_w, top_i, probs = topk_gate(logits, k)   # [N, k]

            # capacity slot per assignment (running count per expert);
            # memory stays O(N*k*E) — the buffers themselves are built
            # with scatter/gather, never an [N,k,E,C] one-hot
            slot, oh = assignment_slots(top_i, E)
            keep = slot < capacity
            disp = dispatch_to_buffers(v, top_i, slot, keep, E, capacity)
            # batched expert SwiGLU: all experts in three MXU einsums
            eo = grouped_expert_swiglu(disp, wg, wu, wd)  # [E,C,D]
            out = combine_from_buffers(eo, top_i, slot, top_w,
                                       keep).astype(v.dtype)

            # Mixtral load-balancing aux: E * sum_e f_e * P_e, with f_e
            # from the RAW assignment (pre-capacity) so router collapse
            # is penalized in full
            frac = jnp.mean(oh.sum(axis=1), axis=0)      # tokens/expert
            pmean = jnp.mean(probs, axis=0)
            aux = E * jnp.sum(frac * pmean)
            return out, aux

        out, aux = apply_op("mixtral_moe", fn,
                            (flat, targ(self.gate.weight),
                             targ(self.w_gate), targ(self.w_up),
                             targ(self.w_down)))
        self.l_aux = aux
        return reshape(out, orig_shape)


class MixtralDecoderLayer(Layer):
    def __init__(self, config: MixtralConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.block_sparse_moe = MixtralSparseMoeBlock(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, x, attn_mask=None):
        h = x + self.self_attn(self.input_layernorm(x), attn_mask)
        return h + self.block_sparse_moe(
            self.post_attention_layernorm(h))

    def forward_with_cache(self, x, cache, position_offset,
                           attn_mask=None):
        attn, new_cache = self.self_attn(
            self.input_layernorm(x), attn_mask, cache=cache,
            position_offset=position_offset)
        h = x + attn
        return h + self.block_sparse_moe(
            self.post_attention_layernorm(h)), new_cache


class MixtralModel(Layer):
    def __init__(self, config: MixtralConfig):
        super().__init__()
        self.config = config
        from ..nn.layers import Embedding
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=_attr(I.Normal(0.0, config.initializer_range)))
        self.layers = LayerList([MixtralDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, caches=None,
                position_offset=0):
        h = self.embed_tokens(input_ids)
        if self.config.dtype == "bfloat16":
            h = h.astype("bfloat16")
        if caches is None:
            for layer in self.layers:
                h = layer(h, attn_mask)
            return self.norm(h)
        new_caches = []
        for layer, cache in zip(self.layers, caches):
            h, c = layer.forward_with_cache(h, cache, position_offset,
                                            attn_mask)
            new_caches.append(c)
        return self.norm(h), new_caches


class MixtralForCausalLM(Layer):
    def __init__(self, config: MixtralConfig):
        super().__init__()
        self.config = config
        self.mixtral = MixtralModel(config)
        self.lm_head = Linear(config.hidden_size, config.vocab_size,
                              weight_attr=_attr(
                                  I.Normal(0.0, config.initializer_range)),
                              bias_attr=False)

    def forward(self, input_ids, attn_mask=None, caches=None,
                position_offset=0):
        if caches is None:
            h = self.mixtral(input_ids, attn_mask)
            return self.lm_head(h)
        h, caches = self.mixtral(input_ids, attn_mask, caches,
                                 position_offset)
        return self.lm_head(h), caches

    # the eager decode loop is model-agnostic (self.forward + config
    # only) — share the llama implementation verbatim so the MoE parity
    # reference can never drift from the dense one
    generate = LlamaForCausalLM.generate

    def router_aux_loss(self):
        """Sum of per-layer load-balancing losses from the LAST forward
        (traced values — combine with the CE loss inside the same
        step/trace), scaled by router_aux_loss_coef."""
        auxes = [lyr.block_sparse_moe.l_aux
                 for lyr in self.mixtral.layers
                 if lyr.block_sparse_moe.l_aux is not None]
        if not auxes:
            raise RuntimeError(
                "router_aux_loss() needs a forward pass first (the aux "
                "terms are recorded per layer during forward)")
        total = auxes[0]
        for a in auxes[1:]:
            total = total + a
        return total * self.config.router_aux_loss_coef


class MixtralPretrainingCriterion(Layer):
    """CE + router load-balancing aux (reads the aux recorded on the
    model by the forward that produced ``logits``)."""

    def __init__(self, model: MixtralForCausalLM):
        super().__init__()
        self._model = [model]          # avoid registering as sublayer

    def forward(self, logits, labels):
        ce = LlamaPretrainingCriterion()(logits, labels)
        return ce + self._model[0].router_aux_loss()


def mixtral_tiny_config(**kw):
    cfg = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=4, max_position_embeddings=128,
               num_local_experts=4, num_experts_per_tok=2)
    cfg.update(kw)
    return MixtralConfig(**cfg)


def shard_mixtral(model: MixtralForCausalLM, mesh, ep_axis="model",
                  fsdp_axis="sharding"):
    """Expert-parallel + FSDP layout: expert banks shard their E dim over
    ``ep_axis`` (GSPMD inserts the dispatch/combine all-to-all); the
    attention/embedding layout matches shard_llama (Megatron columns/
    rows + vocab sharding) with ep_axis standing in for the tp axis;
    router + norms replicate."""
    from ..distributed.api import shard_param_
    from .llama import axis_placements

    def placements(ep_dim=None, fsdp_dim=None):
        return axis_placements(mesh, **{ep_axis: ep_dim,
                                        fsdp_axis: fsdp_dim})

    shard_param_(model.mixtral.embed_tokens.weight, mesh,
                 placements(ep_dim=0, fsdp_dim=1))
    shard_param_(model.lm_head.weight, mesh,
                 placements(ep_dim=1, fsdp_dim=0))
    for layer in model.mixtral.layers:
        a = layer.self_attn
        for lin in (a.q_proj, a.k_proj, a.v_proj):
            shard_param_(lin.weight, mesh,
                         placements(ep_dim=1, fsdp_dim=0))
        shard_param_(a.o_proj.weight, mesh,
                     placements(ep_dim=0, fsdp_dim=1))
        moe = layer.block_sparse_moe
        for w in (moe.w_gate, moe.w_up, moe.w_down):
            shard_param_(w, mesh, placements(ep_dim=0, fsdp_dim=2))
