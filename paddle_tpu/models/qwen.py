"""Qwen2-family causal LM.

Capability parity with the PaddleNLP Qwen2 modeling the reference
ecosystem ships (qwen2 = llama architecture + qkv biases + optional tied
embeddings; reference architecture family: paddlenlp/transformers/qwen2).
TPU-native: reuses the LlamaForCausalLM stack (flash attention, ring/
Ulysses sequence parallelism, recompute) with the qwen2 switches set —
the same composition HF/PaddleNLP use rather than a duplicated tower.
"""
from __future__ import annotations

from dataclasses import dataclass

from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPretrainingCriterion, param_count)

__all__ = ["Qwen2Config", "Qwen2Model", "Qwen2ForCausalLM",
           "Qwen2PretrainingCriterion", "qwen2_tiny_config"]


@dataclass
class Qwen2Config(LlamaConfig):
    vocab_size: int = 151936
    hidden_size: int = 3584
    intermediate_size: int = 18944
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    max_position_embeddings: int = 32768
    rope_theta: float = 1000000.0
    attention_bias: bool = True          # the qwen2 signature difference
    tie_word_embeddings: bool = False


def qwen2_tiny_config(**kw) -> Qwen2Config:
    cfg = dict(vocab_size=1024, hidden_size=128, intermediate_size=352,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=256)
    cfg.update(kw)
    return Qwen2Config(**cfg)


class Qwen2Model(LlamaModel):
    """Decoder stack with qwen2 switches (GQA + qkv biases)."""


class Qwen2ForCausalLM(LlamaForCausalLM):
    """Parity surface: Qwen2ForCausalLM — same generate/caching path as
    the llama flagship."""

    def __init__(self, config: Qwen2Config):
        if not getattr(config, "attention_bias", False):
            raise ValueError("Qwen2Config requires attention_bias=True")
        super().__init__(config)


Qwen2PretrainingCriterion = LlamaPretrainingCriterion
